"""Serving app tests: in-process dispatch + micro-batcher. The live-socket HTTP
framing tests (chunked streaming, HTTP/1.0 fallback, keep-alive) and the
CLI-booted subprocess server live in tests/integration/."""

import asyncio
import json

import pytest

from unionml_tpu.serving import MicroBatcher, ServingConfig, serving_app


def _dispatch(app, method, path, body=b""):
    return asyncio.run(app.dispatch(method, path, body))


@pytest.fixture
def trained_app(sklearn_model):
    sklearn_model.train(hyperparameters={"max_iter": 500})
    return serving_app(sklearn_model)


def test_root_banner(trained_app):
    status, payload, content_type = _dispatch(trained_app, "GET", "/")
    assert status == 200
    assert content_type == "text/html"
    assert "unionml-tpu" in payload


def test_health(trained_app):
    status, payload, _ = _dispatch(trained_app, "GET", "/health")
    assert status == 200
    assert payload["status"] == 200


def test_health_without_artifact(sklearn_model):
    app = serving_app(sklearn_model)
    app._started = True  # skip startup loading
    status, payload, _ = _dispatch(app, "GET", "/health")
    assert status == 500
    assert "not found" in payload["detail"].lower()


def test_predict_with_features(trained_app):
    body = json.dumps({"features": [{"x1": 1.0, "x2": 1.0}, {"x1": -1.0, "x2": -1.0}]}).encode()
    status, payload, _ = _dispatch(trained_app, "POST", "/predict", body)
    assert status == 200
    assert payload == [1.0, 0.0]


def test_predict_with_inputs(trained_app):
    body = json.dumps({"inputs": {"sample_frac": 1.0, "random_state": 0}}).encode()
    status, payload, _ = _dispatch(trained_app, "POST", "/predict", body)
    assert status == 200
    assert len(payload) == 100


def test_predict_requires_inputs_or_features(trained_app):
    status, payload, _ = _dispatch(trained_app, "POST", "/predict", b"{}")
    assert status == 500
    assert "inputs or features" in payload["detail"]


def test_predict_invalid_json(trained_app):
    status, payload, _ = _dispatch(trained_app, "POST", "/predict", b"{not json")
    assert status == 400


def test_unknown_route_and_method(trained_app):
    status, *_ = _dispatch(trained_app, "GET", "/nope")
    assert status == 404
    status, *_ = _dispatch(trained_app, "DELETE", "/predict")
    assert status == 405


def test_startup_requires_model_path(sklearn_model, monkeypatch):
    monkeypatch.delenv("UNIONML_MODEL_PATH", raising=False)
    app = serving_app(sklearn_model)
    with pytest.raises(ValueError, match="artifact path not specified"):
        asyncio.run(app.dispatch("GET", "/health"))


def test_startup_loads_from_env(sklearn_model, tmp_path, monkeypatch):
    sklearn_model.train(hyperparameters={"max_iter": 500})
    path = tmp_path / "m.joblib"
    sklearn_model.save(str(path))
    sklearn_model.artifact = None
    monkeypatch.setenv("UNIONML_MODEL_PATH", str(path))
    app = serving_app(sklearn_model)
    status, *_ = _dispatch(app, "GET", "/health")
    assert status == 200


def test_micro_batcher_coalesces_requests():
    calls = []

    def predict(batch):
        calls.append(len(batch))
        return [x * 2 for x in batch]

    async def scenario():
        batcher = MicroBatcher(predict, ServingConfig(max_batch_size=8, max_wait_ms=50))
        results = await asyncio.gather(*(batcher.submit([i]) for i in range(6)))
        await batcher.stop()
        return results

    results = asyncio.run(scenario())
    assert sorted(r[0] for r in results) == [0, 2, 4, 6, 8, 10]
    assert len(calls) < 6  # at least some requests shared a dispatch
    buckets = ServingConfig(max_batch_size=8).buckets()
    assert all(n in buckets for n in calls)  # dispatches are padded to bucket shapes


def test_micro_batcher_mismatched_signatures_never_share_a_concat():
    """Default-on batching must not pd.concat frames with different columns
    (the union would NaN-fill and silently corrupt predictions): a signature
    change flushes the current batch and starts the next one."""
    import pandas as pd

    seen = []

    def predict(batch):
        seen.append(tuple(batch.columns))
        assert not batch.isna().any().any()  # a NaN here = corrupted concat
        return list(batch.iloc[:, 0] * 2)

    async def scenario():
        batcher = MicroBatcher(predict, ServingConfig(max_batch_size=8, max_wait_ms=50, pad_to_bucket=False))
        a = pd.DataFrame({"x": [1.0]})
        b = pd.DataFrame({"y": [10.0], "z": [0.0]})
        return await asyncio.gather(
            batcher.submit(a), batcher.submit(b), batcher.submit(a * 3)
        )

    ra, rb, ra3 = asyncio.run(scenario())
    assert ra == [2.0] and rb == [20.0] and ra3 == [6.0]
    assert all(cols in (("x",), ("y", "z")) for cols in seen)


def test_micro_batcher_non_row_aligned_output_falls_back_and_pins_solo():
    """A predictor whose output is not one-row-per-input (here: a scalar
    aggregate) cannot be sliced per request — the first coalesced dispatch
    detects it, reruns each request individually (exact no-batcher semantics),
    and pins the solo path so later batches never pay a doomed combined call."""
    calls = []

    def predict(batch):
        calls.append(len(batch))
        return float(sum(batch))  # scalar: not a row-major container

    async def scenario():
        batcher = MicroBatcher(predict, ServingConfig(max_batch_size=8, max_wait_ms=50, pad_to_bucket=False))
        first = await asyncio.gather(batcher.submit([1, 2]), batcher.submit([10]))
        second = await asyncio.gather(batcher.submit([5]), batcher.submit([6, 7]))
        return first, second, batcher._row_aligned

    (r1, r2), (r3, r4), aligned = asyncio.run(scenario())
    assert (r1, r2) == (3.0, 10.0)  # each request saw ITS OWN aggregate
    assert (r3, r4) == (5.0, 13.0)
    assert aligned is False  # pinned: the second round dispatched solo-only
    assert calls.count(3) <= 1  # at most the one detection dispatch was combined


def test_micro_batcher_tuple_output_is_never_sliced_across_callers():
    """A structured output whose len() coincidentally equals the batch rows —
    (predictions, probabilities) from a 2-row batch — must not be split, or
    caller 1 would receive the predictions and caller 2 the probabilities."""
    def predict(batch):
        return ([x * 2 for x in batch], [0.5 for _ in batch])

    async def scenario():
        batcher = MicroBatcher(predict, ServingConfig(max_batch_size=8, max_wait_ms=50, pad_to_bucket=False))
        return await asyncio.gather(batcher.submit([1]), batcher.submit([3]))

    r1, r2 = asyncio.run(scenario())
    assert r1 == ([2], [0.5]) and r2 == ([6], [0.5])


def test_micro_batcher_unconcatenatable_features_never_share_a_batch():
    """Feature types _concat cannot merge (e.g. dicts from a custom
    feature_loader) get per-object signatures: concurrent requests each ride
    the single-request path instead of failing both with a concat TypeError."""
    def predict(features):
        return {"n": features["n"] * 2}

    async def scenario():
        batcher = MicroBatcher(predict, ServingConfig(max_batch_size=8, max_wait_ms=50, pad_to_bucket=False))
        return await asyncio.gather(batcher.submit({"n": 1}), batcher.submit({"n": 5}))

    r1, r2 = asyncio.run(scenario())
    assert r1 == {"n": 2} and r2 == {"n": 10}


def test_micro_batcher_same_unconcatenatable_object_dispatches_solo():
    """A SHARED object (a memoized dict reused across requests) has
    identity-equal signatures, so it CAN share a batch — the failed concat must
    then degrade to solo dispatches, never a batched 500."""
    shared = {"n": 4}

    def predict(features):
        return {"n": features["n"] * 2}

    async def scenario():
        batcher = MicroBatcher(predict, ServingConfig(max_batch_size=8, max_wait_ms=50, pad_to_bucket=False))
        return await asyncio.gather(batcher.submit(shared), batcher.submit(shared))

    r1, r2 = asyncio.run(scenario())
    assert r1 == {"n": 8} and r2 == {"n": 8}


def test_micro_batcher_ragged_list_rows_never_share_a_concat():
    """List features whose rows have different widths must not concatenate
    (the predictor would see a ragged batch): the width rides the signature."""
    def predict(batch):
        widths = {len(r) for r in batch}
        assert len(widths) == 1, f"ragged batch reached the predictor: {widths}"
        return [sum(r) for r in batch]

    async def scenario():
        batcher = MicroBatcher(predict, ServingConfig(max_batch_size=8, max_wait_ms=50, pad_to_bucket=False))
        return await asyncio.gather(
            batcher.submit([[1, 2]]), batcher.submit([[3, 4, 5]]), batcher.submit([[6, 7]])
        )

    r1, r2, r3 = asyncio.run(scenario())
    assert (r1, r2, r3) == ([3], [12], [13])


def test_micro_batcher_stats_count_solo_reruns_as_dispatches():
    """avg_rows_per_dispatch must reflect REALIZED vectorization: an app pinned
    to the solo path reads ~1 row per predictor invocation, not its batch size."""
    def predict(batch):
        return float(sum(batch))  # non-row-aligned: pins the solo path

    async def scenario():
        batcher = MicroBatcher(predict, ServingConfig(max_batch_size=8, max_wait_ms=50, pad_to_bucket=False))
        await asyncio.gather(batcher.submit([1]), batcher.submit([2]))  # detection round
        await asyncio.gather(batcher.submit([3]), batcher.submit([4]))  # pinned round
        return batcher.stats()

    stats = asyncio.run(scenario())
    assert stats["row_aligned"] is False
    assert stats["requests"] == 4 and stats["rows"] == 4
    # >= one invocation per request (plus the one doomed detection call):
    # avg rows/dispatch stays ~1, never inflated by counted-but-absent batching
    assert stats["dispatches"] >= 4
    assert stats["avg_rows_per_dispatch"] <= 1.0


def test_micro_batcher_scalar_array_output_falls_back_to_solo():
    """A 0-d (unsized) predictor output — e.g. np.sum over the batch — passes
    the row-major type check but raises TypeError from len(); that used to
    escape the not-row-aligned guard and 500 EVERY coalesced batch, forever.
    It must instead pin the solo path like any other aggregate output."""
    import numpy as np

    calls = []

    def predict(batch):
        calls.append(len(batch))
        return np.sum(np.asarray(batch, dtype=np.float64))  # 0-d ndarray

    async def scenario():
        batcher = MicroBatcher(predict, ServingConfig(max_batch_size=8, max_wait_ms=50, pad_to_bucket=False))
        first = await asyncio.gather(batcher.submit([1, 2]), batcher.submit([10]))
        second = await asyncio.gather(batcher.submit([5]), batcher.submit([6, 7]))
        return first, second, batcher._row_aligned

    (r1, r2), (r3, r4), aligned = asyncio.run(scenario())
    assert (float(r1), float(r2)) == (3.0, 10.0)  # each request saw ITS OWN sum
    assert (float(r3), float(r4)) == (5.0, 13.0)
    assert aligned is False  # pinned: later rounds dispatch solo, not doomed-combined
    assert calls.count(3) <= 1  # at most the one detection dispatch was combined


def test_micro_batcher_solo_rerun_isolates_bad_requests():
    """On the pinned solo path, one request whose predictor rerun raises must
    fail ONLY its own future — the valid siblings queued behind it in the same
    batch keep their results."""
    def predict(batch):
        if any(x < 0 for x in batch):
            raise ValueError("negative feature")
        return float(sum(batch))  # scalar aggregate: pins the solo path

    async def scenario():
        batcher = MicroBatcher(predict, ServingConfig(max_batch_size=8, max_wait_ms=50, pad_to_bucket=False))
        await asyncio.gather(batcher.submit([1]), batcher.submit([2]))  # pins solo
        assert batcher._row_aligned is False
        return await asyncio.gather(
            batcher.submit([3]), batcher.submit([-5]), batcher.submit([4]),
            return_exceptions=True,
        )

    good_before, bad, good_after = asyncio.run(scenario())
    assert good_before == 3.0
    assert isinstance(bad, ValueError)
    assert good_after == 4.0  # the sibling AFTER the failure still resolved


def test_serving_app_batches_by_default(sklearn_model):
    """Predictors registered without a ServingConfig still get a MicroBatcher
    (measured ~2x on the digits quickstart under 16-way concurrency); a
    single-request dispatch hands the output through whole."""
    from unionml_tpu.serving import serving_app

    app = serving_app(sklearn_model)
    assert app.batcher is not None
    assert app.batcher.config.max_batch_size > 1
    assert app.batcher.config.warmup is False  # no config -> no AOT machinery


def test_metrics_reports_micro_batcher_telemetry(trained_app):
    """The coalescing lever is observable: /metrics carries dispatch/request/
    row counters and the row-alignment pin state."""
    body = json.dumps({"features": [{"x1": 1.0, "x2": 1.0}]}).encode()
    for _ in range(3):
        status, _, _ = _dispatch(trained_app, "POST", "/predict", body)
        assert status == 200
    status, payload, _ = _dispatch(trained_app, "GET", "/metrics")
    assert status == 200
    mb = payload["micro_batcher"]
    assert mb["dispatches"] >= 1 and mb["requests"] >= mb["dispatches"]
    # coalescing telemetry plus the overload block (bounded admission)
    assert {"dispatches", "requests", "rows", "avg_rows_per_dispatch", "row_aligned"} <= set(mb)
    assert {"queue_depth", "max_queue", "shed_queue_full", "shed_deadline", "cancelled"} <= set(mb)
    assert mb["shed_queue_full"] == 0 and mb["queue_depth"] == 0  # healthy, unloaded


def test_metrics_surfaces_replica_generation_engine(trained_app):
    """An app whose generation engine is a ReplicaSet gets per-replica
    occupancy on /metrics twice over: the engine's stats() under "generation"
    and the live "generation_replicas" gauge — absent (not null) while the
    engine is a single ContinuousBatcher or not built yet."""
    status, payload, _ = _dispatch(trained_app, "GET", "/metrics")
    assert status == 200
    assert "generation_replicas" not in payload.get("gauges", {})  # inactive gauge stays out

    class _FakeReplicaEngine:
        def stats(self):
            return {"replicas": 2, "per_replica": [{"slots": 2}, {"slots": 2}]}

        def replica_loads(self):
            return [
                {"replica": 0, "resident": 1, "waiting": 0, "free_slots": 1},
                {"replica": 1, "resident": 2, "waiting": 3, "free_slots": 0},
            ]

    trained_app.model.generation_batcher = _FakeReplicaEngine()
    try:
        status, payload, _ = _dispatch(trained_app, "GET", "/metrics")
        assert status == 200
        assert payload["generation"]["replicas"] == 2
        assert len(payload["generation"]["per_replica"]) == 2
        gauge = payload["gauges"]["generation_replicas"]
        assert gauge[1]["waiting"] == 3 and gauge[0]["resident"] == 1
    finally:
        trained_app.model.generation_batcher = None


def test_serving_config_max_batch_size_one_disables_the_batcher(sklearn_model):
    """The documented opt-out: max_batch_size=1 means NO batcher — requests run
    straight through the predictor (the no-batcher code paths stay live)."""
    from unionml_tpu.serving import serving_app

    sklearn_model._predictor_config = ServingConfig(max_batch_size=1, jit=False, warmup=False)
    try:
        app = serving_app(sklearn_model)
        assert app.batcher is None
    finally:
        sklearn_model._predictor_config = None


def test_micro_batcher_sparse_requests_skip_the_wait_window():
    """Adaptive wait: with an empty queue and no recent coalescing, a solo
    request dispatches immediately instead of idling out max_wait_ms — sparse
    traffic pays ~zero added latency (measured 8 -> 2.5 ms p50 live)."""
    import time

    def predict(batch):
        return [x * 2 for x in batch]

    async def scenario():
        batcher = MicroBatcher(predict, ServingConfig(max_batch_size=8, max_wait_ms=500, pad_to_bucket=False))
        t0 = time.perf_counter()
        out = await batcher.submit([21])
        return out, time.perf_counter() - t0

    out, elapsed = asyncio.run(scenario())
    assert out == [42]
    assert elapsed < 0.25, f"solo request waited {elapsed*1000:.0f} ms of a 500 ms window"


def test_micro_batcher_propagates_errors():
    def predict(batch):
        raise RuntimeError("boom")

    async def scenario():
        batcher = MicroBatcher(predict, ServingConfig(max_batch_size=4, max_wait_ms=5))
        with pytest.raises(RuntimeError, match="boom"):
            await batcher.submit([1])
        await batcher.stop()

    asyncio.run(scenario())


def test_metrics_endpoint_reports_latency_percentiles(trained_app):
    for _ in range(5):
        body = json.dumps({"features": [{"x1": 1.0, "x2": 1.0}]}).encode()
        status, _, _ = _dispatch(trained_app, "POST", "/predict", body)
        assert status == 200
    _dispatch(trained_app, "POST", "/predict", b"not json")  # counted as an error

    status, snapshot, _ = _dispatch(trained_app, "GET", "/metrics")
    assert status == 200
    assert snapshot["requests_total"] >= 6
    assert snapshot["errors_total"] >= 1
    predict = snapshot["routes"]["POST /predict"]
    assert predict["requests"] >= 6 and predict["errors"] >= 1
    assert predict["p50_ms"] > 0 and predict["p99_ms"] >= predict["p50_ms"]


def test_predict_stream_requires_registration(trained_app):
    status, payload, _ = _dispatch(
        trained_app, "POST", "/predict-stream", json.dumps({"features": []}).encode()
    )
    assert status == 404
    assert "stream predictor" in payload["detail"]


def test_predict_stream_setup_error_is_500_not_truncated_200(sklearn_model):
    """Generator-function predictors defer their body to the first next(); the
    route must surface that first failure as a clean 500, not a truncated 200."""
    sklearn_model.train(hyperparameters={"max_iter": 500})

    @sklearn_model.stream_predictor
    def stream_predictor(model_object, features):
        raise RuntimeError("boom")
        yield  # pragma: no cover

    app = serving_app(sklearn_model)
    status, payload, _ = _dispatch(
        app, "POST", "/predict-stream", json.dumps({"features": [{"x": 1.0}]}).encode()
    )
    assert status == 500 and "boom" in payload["detail"]

    # body contract matches /predict: a non-dict JSON body is a 400
    status, payload, _ = _dispatch(app, "POST", "/predict-stream", b"[1, 2]")
    assert status == 400 and "JSON object" in payload["detail"]
