"""Model lifecycle tests — mirrors reference tests/unit/test_model.py coverage."""

import io
from typing import List

import pandas as pd
import pytest

from unionml_tpu import Dataset, ExecutionGraph, Model, stage
from unionml_tpu.model import BaseHyperparameters


def test_train_task_interface(sklearn_model: Model):
    train_stage = sklearn_model.train_task()
    inputs = train_stage.interface.inputs
    assert list(inputs)[:2] == ["hyperparameters", "data"]
    assert set(("loader_kwargs", "splitter_kwargs", "parser_kwargs")) <= set(inputs)
    assert list(train_stage.interface.outputs) == ["model_object", "hyperparameters", "metrics"]


def test_hyperparameter_type_synthesis(simple_dataset):
    def init(C: float = 1.0, max_iter: int = 100) -> object:
        ...

    model = Model(name="m", init=init, dataset=simple_dataset)
    hp_type = model.hyperparameter_type
    assert issubclass(hp_type, BaseHyperparameters)
    hp = hp_type()
    assert hp.C == 1.0 and hp.max_iter == 100
    assert hp_type.from_json(hp.to_json()) == hp


def test_hyperparameter_type_untyped_init_falls_back_to_dict(simple_dataset):
    def init(C=1.0):
        ...

    model = Model(name="m", init=init, dataset=simple_dataset)
    assert model.hyperparameter_type is dict


def test_hyperparameter_config_override(simple_dataset):
    model = Model(name="m", dataset=simple_dataset, hyperparameter_config={"alpha": float})
    hp = model.hyperparameter_type(alpha=0.5)
    assert hp.alpha == 0.5


def test_local_train(sklearn_model: Model):
    model_obj, metrics = sklearn_model.train(hyperparameters={"max_iter": 500})
    assert model_obj is sklearn_model.artifact.model_object
    assert set(metrics) == {"train", "test"}
    assert metrics["train"] > 0.8


def test_local_train_with_stage_kwargs(sklearn_model: Model):
    _, metrics = sklearn_model.train(
        hyperparameters={"max_iter": 500},
        splitter_kwargs={"test_size": 0.5},
        sample_frac=1.0,
    )
    assert set(metrics) == {"train", "test"}


def test_predict_from_reader_vs_features_equivalence(sklearn_model: Model):
    sklearn_model.train(hyperparameters={"max_iter": 500})
    preds_reader = sklearn_model.predict(sample_frac=1.0, random_state=0)
    raw = sklearn_model.dataset.dataset_task()(sample_frac=1.0, random_state=0)
    features = raw[["x1", "x2"]].to_dict(orient="records")
    preds_features = sklearn_model.predict(features=features)
    assert preds_reader == preds_features


def test_predict_without_training_raises(sklearn_model: Model):
    with pytest.raises(RuntimeError, match="ModelArtifact not found"):
        sklearn_model.predict(sample_frac=1.0)


def test_predict_requires_features_or_reader_kwargs(sklearn_model: Model):
    with pytest.raises(ValueError, match="At least one of features"):
        sklearn_model.predict()


def test_save_load_path(sklearn_model: Model, tmp_path):
    sklearn_model.train(hyperparameters={"max_iter": 500})
    path = tmp_path / "model.joblib"
    sklearn_model.save(str(path))

    preds_before = sklearn_model.predict(sample_frac=1.0, random_state=0)
    sklearn_model.artifact = None
    sklearn_model.load(str(path))
    preds_after = sklearn_model.predict(sample_frac=1.0, random_state=0)
    assert preds_before == preds_after


def test_save_load_fileobj(sklearn_model: Model):
    sklearn_model.train(hyperparameters={"max_iter": 500})
    buf = io.BytesIO()
    sklearn_model.save(buf)
    buf.seek(0)
    loaded = sklearn_model._loader(buf)
    assert loaded.coef_.shape == sklearn_model.artifact.model_object.coef_.shape


def test_load_from_env(sklearn_model: Model, tmp_path, monkeypatch):
    sklearn_model.train(hyperparameters={"max_iter": 500})
    path = tmp_path / "model.joblib"
    sklearn_model.save(str(path))
    monkeypatch.setenv("UNIONML_MODEL_PATH", str(path))
    obj = sklearn_model.load_from_env()
    assert obj is sklearn_model.artifact.model_object


def test_keras_branch_dispatch_and_guard(tmp_path):
    """The keras saver branch dispatches on module sniffing without importing
    tensorflow, and loading without tensorflow raises a clear guidance error
    (reference treats keras as first-class: unionml/model.py:957-984)."""
    from unionml_tpu.artifact import load_model_object, save_model_object
    from unionml_tpu.utils import is_keras_model

    saved = {}

    class FakeKerasModel:
        pass

    FakeKerasModel.__module__ = "keras.engine.training"
    assert is_keras_model(FakeKerasModel)

    FakeKerasModel.save = lambda self, file, *a, **k: saved.setdefault("file", file)
    obj = FakeKerasModel()
    out = tmp_path / "keras_model"
    save_model_object(obj, {}, str(out))
    assert saved["file"] == str(out)  # dispatched to the keras branch, not pickle

    try:
        import tensorflow  # noqa: F401
    except ImportError:
        with pytest.raises(RuntimeError, match="requires tensorflow"):
            load_model_object(str(out), FakeKerasModel)


def test_keras_save_load_roundtrip(tmp_path):
    """Real keras model through the default saver/loader branch (reference
    unionml/model.py:957-984): weights survive the round trip."""
    keras = pytest.importorskip("tensorflow.keras")
    import numpy as np

    from unionml_tpu.artifact import load_model_object, save_model_object

    model = keras.Sequential([keras.layers.Input((4,)), keras.layers.Dense(3)])
    path = tmp_path / "model.keras"
    save_model_object(model, {}, str(path))
    loaded = load_model_object(str(path), type(model))
    x = np.ones((2, 4), dtype="float32")
    np.testing.assert_allclose(loaded.predict(x, verbose=0), model.predict(x, verbose=0))


def test_custom_saver_loader(sklearn_model: Model, tmp_path):
    import joblib

    @sklearn_model.saver
    def saver(model_obj, hyperparameters, file):
        joblib.dump(model_obj, file)
        return file

    @sklearn_model.loader
    def loader(file):
        return joblib.load(file)

    sklearn_model.train(hyperparameters={"max_iter": 500})
    path = tmp_path / "custom.joblib"
    sklearn_model.save(str(path))
    sklearn_model.load(str(path))
    assert sklearn_model.artifact is not None


def test_model_stages_in_custom_graph(sklearn_model: Model):
    """unionml stages embed in hand-written graphs (reference test_model.py:145-196)."""
    sklearn_model.train(hyperparameters={"max_iter": 500})

    @stage
    def select_columns(data: pd.DataFrame) -> pd.DataFrame:
        return data[["x1", "x2"]]

    graph = ExecutionGraph("custom_predict")
    graph.add_input("model_object", object)
    graph.add_input("sample_frac", float)
    graph.add_input("random_state", int)
    reader_node = graph.add_node(
        sklearn_model.dataset.dataset_task(),
        sample_frac=graph.inputs["sample_frac"],
        random_state=graph.inputs["random_state"],
    )
    select_node = graph.add_node(select_columns, data=reader_node.outputs["data"])
    predict_node = graph.add_node(
        sklearn_model.predict_from_features_task(),
        model_object=graph.inputs["model_object"],
        features=select_node.outputs["o0"],
    )
    out_key = list(predict_node.outputs)[0]
    graph.add_output("predictions", predict_node.outputs[out_key])

    preds = graph(
        model_object=sklearn_model.artifact.model_object, sample_frac=1.0, random_state=0
    )
    assert isinstance(preds, list) and len(preds) == 100


def test_trainer_type_guard_rejects_bad_signature(simple_dataset):
    from sklearn.linear_model import LogisticRegression

    model = Model(name="m", init=LogisticRegression, dataset=simple_dataset)
    with pytest.raises(TypeError):

        @model.trainer
        def trainer(estimator: LogisticRegression, features: int, target: int) -> LogisticRegression:
            return estimator


def test_workflow_names(sklearn_model: Model):
    assert sklearn_model.train_workflow_name == "test_model.train"
    assert sklearn_model.predict_workflow_name == "test_model.predict"
    assert sklearn_model.predict_from_features_workflow_name == "test_model.predict_from_features"
