"""Fleet health & SLO engine (observability/{slo,health}.py + the serving
integration): burn-rate state transitions, health scoring, breach exemplars,
the route-around-breach scheduler policy, and the /healthz + /debug/fleet
surface.

The state-machine tests drive an injectable fake clock — no sleeps, no flakes.
"""

import asyncio
import json

import numpy as np
import pytest

from unionml_tpu.observability.health import STATE_FACTORS, engine_health, fleet_debug, fleet_health
from unionml_tpu.observability.recorder import FlightRecorder
from unionml_tpu.observability.slo import SLOConfig, SLOTracker, worst_state
from unionml_tpu.observability.timeseries import EngineTimeseries
from unionml_tpu.observability.trace import RequestTrace
from unionml_tpu.serving.metrics import LatencyWindow
from unionml_tpu.serving.replicas import ReplicaScheduler


class FakeClock:
    def __init__(self, t: float = 0.0):
        self.t = t

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += dt


def _timeseries(clock) -> EngineTimeseries:
    return EngineTimeseries(
        clock=clock, horizon_s=700.0,
        ttft=LatencyWindow(clock=clock), tbt=LatencyWindow(clock=clock),
    )


# ------------------------------------------------------------------ SLOConfig


def test_slo_config_validation_and_armed():
    assert not SLOConfig().armed
    assert SLOConfig(ttft_p95_ms=250.0).armed
    with pytest.raises(ValueError):
        SLOConfig(ttft_p95_ms=-1.0)
    with pytest.raises(ValueError):
        SLOConfig(fast_window_s=120.0, slow_window_s=60.0)
    with pytest.raises(ValueError):
        SLOConfig(min_samples=0)


def test_slo_config_from_env_warn_and_fall_back(monkeypatch):
    monkeypatch.setenv("UNIONML_TPU_SLO_TTFT_P95_MS", "250")
    monkeypatch.setenv("UNIONML_TPU_SLO_TBT_P99_MS", "garbage")  # degrades, no crash
    monkeypatch.setenv("UNIONML_TPU_SLO_SHED_RATIO", "0.05")
    config = SLOConfig.from_env()
    assert config.ttft_p95_ms == 250.0
    assert config.tbt_p99_ms is None
    assert config.shed_ratio == 0.05
    # cross-value garbage (fast > slow) widens the slow window instead of raising
    monkeypatch.setenv("UNIONML_TPU_SLO_FAST_WINDOW_S", "900")
    config = SLOConfig.from_env()
    assert config.fast_window_s == 900.0 and config.slow_window_s == 900.0


# ------------------------------------------------------- burn-rate transitions


def test_burn_rate_state_machine_up_and_down():
    """ok -> warn (fast window breaches, slow not yet) -> breach (both) ->
    warn (fast recovers while the slow window still holds the incident) ->
    ok (the incident ages out of the slow window): breach never snaps
    straight to ok."""
    clock = FakeClock()
    ts = _timeseries(clock)
    tracker = SLOTracker(SLOConfig(
        ttft_p95_ms=100.0, fast_window_s=60.0, slow_window_s=600.0, min_samples=1,
    ))
    assert tracker.evaluate(ts)["state"] == "ok"  # idle engine is healthy

    # healthy baseline: enough good samples that one bad one cannot move the
    # slow window's p95
    for _ in range(60):
        ts.ttft.observe(0.010)
    assert tracker.evaluate(ts)["state"] == "ok"

    # fresh regression: the fast window sees only the bad samples, the slow
    # p95 still rides the baseline -> warn (early warning, not yet confirmed)
    clock.advance(120.0)
    for _ in range(2):
        ts.ttft.observe(0.500)
    out = tracker.evaluate(ts)
    assert out["state"] == "warn"
    obj = out["objectives"]["ttft_p95_ms"]
    assert obj["fast"]["value"] > 100.0 > obj["slow"]["value"]
    assert obj["fast"]["burn_rate"] == pytest.approx(5.0)

    # sustained: bad samples dominate the slow window too -> breach
    for _ in range(60):
        ts.ttft.observe(0.500)
    assert tracker.evaluate(ts)["state"] == "breach"

    # recovery: traffic stops; the fast window drains first -> warn, not ok
    clock.advance(120.0)
    assert tracker.evaluate(ts)["state"] == "warn"

    # the slow window finally forgets the incident -> ok
    clock.advance(600.0)
    assert tracker.evaluate(ts)["state"] == "ok"


def test_min_samples_gate_keeps_idle_engines_healthy():
    clock = FakeClock()
    ts = _timeseries(clock)
    tracker = SLOTracker(SLOConfig(ttft_p95_ms=10.0, min_samples=3))
    ts.ttft.observe(5.0)  # one terrible sample, below the gate
    out = tracker.evaluate(ts)
    assert out["state"] == "ok"
    assert out["objectives"]["ttft_p95_ms"]["fast"]["samples"] == 1


def test_shed_ratio_objective():
    clock = FakeClock()
    ts = _timeseries(clock)
    tracker = SLOTracker(SLOConfig(shed_ratio=0.10, min_samples=5))
    for _ in range(18):
        ts.admissions.add()
    ts.sheds.add(2)  # 10% exactly -> not a breach (> target, not >=)
    assert tracker.evaluate(ts)["state"] == "ok"
    ts.sheds.add(3)  # ~22% -> both windows over target
    out = tracker.evaluate(ts)
    assert out["state"] == "breach"
    assert out["objectives"]["shed_ratio"]["fast"]["value"] > 0.10


def test_worst_state_ordering():
    assert worst_state([]) == "ok"
    assert worst_state(["ok", "warn"]) == "warn"
    assert worst_state(["warn", "breach", "ok"]) == "breach"


# ----------------------------------------------------------- breach exemplars


def test_note_marks_trace_and_counts_breaches():
    tracker = SLOTracker(SLOConfig(ttft_p95_ms=100.0, tbt_p99_ms=50.0))
    trace = RequestTrace("r-1", "POST", "/gen")
    tracker.note_ttft(trace, 80.0)  # under target: no mark
    assert trace.slo_breach is None and tracker.breached_requests == 0
    tracker.note_ttft(trace, 250.0)
    tracker.note_tbt(None, 75.0)  # untraced requests still count
    assert tracker.breached_requests == 2
    snap = trace.snapshot()
    assert snap["slo_breach"]["objective"] == "ttft_p95_ms"
    assert snap["slo_breach"]["observed_ms"] == pytest.approx(250.0)
    assert any(e["event"] == "slo.breach" for e in snap["events"])


def test_mark_slo_breach_keeps_worst_and_counts_repeats():
    trace = RequestTrace("r-2", "GET", "/x")
    trace.mark_slo_breach("tbt_p99_ms", 60.0, 50.0)
    trace.mark_slo_breach("tbt_p99_ms", 90.0, 50.0)
    trace.mark_slo_breach("tbt_p99_ms", 70.0, 50.0)
    snap = trace.snapshot()
    assert snap["slo_breach"]["count"] == 3
    assert snap["slo_breach"]["observed_ms"] == pytest.approx(90.0)
    # one slo.breach event, not one per stutter
    assert sum(1 for e in snap["events"] if e["event"] == "slo.breach") == 1


def _completed_trace(recorder, rid, breach=False, duration_s=0.0):
    trace = RequestTrace(rid, "POST", "/gen")
    recorder.start(trace)
    if breach:
        trace.mark_slo_breach("ttft_p95_ms", 500.0, 100.0)
    if duration_s:
        # seal with a synthetic duration by back-dating t0 (monotonic offsets)
        trace.t0 -= duration_s
    trace.finish(200)
    recorder.complete(trace)
    return trace


def test_recorder_pins_breaching_timelines_into_exemplar_ring():
    recorder = FlightRecorder(4, exemplar_capacity=8)
    _completed_trace(recorder, "ok-1")
    _completed_trace(recorder, "bad-1", breach=True)
    for i in range(6):  # churn the main ring far past capacity
        _completed_trace(recorder, f"churn-{i}")
    assert recorder.exemplar_count == 1
    snap = recorder.snapshot(slo_breach=True)
    assert [s["request_id"] for s in snap["completed"]] == ["bad-1"]
    assert snap["exemplars"] == 1
    # the exemplar outlived its eviction from the main ring
    assert all(s["request_id"] != "bad-1" for s in recorder.snapshot()["completed"])
    assert recorder.get("bad-1")["slo_breach"]["objective"] == "ttft_p95_ms"


def test_recorder_min_ms_filter_and_duration_in_list_view():
    recorder = FlightRecorder(8)
    _completed_trace(recorder, "fast", duration_s=0.001)
    _completed_trace(recorder, "slow", duration_s=2.0)
    snap = recorder.snapshot()
    assert all("duration_ms" in s for s in snap["completed"])
    slow_only = recorder.snapshot(min_ms=1000.0)
    assert [s["request_id"] for s in slow_only["completed"]] == ["slow"]
    assert slow_only["completed"][0]["duration_ms"] >= 1000.0


# ------------------------------------------------------------- health scoring


class FakeEngine:
    """Duck-typed engine surface health.engine_health consumes."""

    slots = 4
    max_waiting = 8
    _load_norm = 16.0

    def __init__(self, clock, config=None, resident=0, waiting=0, backlog=0):
        self.timeseries = _timeseries(clock)
        self.slo = SLOTracker(config or SLOConfig(ttft_p95_ms=100.0, min_samples=1))
        self._occ = (resident, waiting)
        self._backlog = backlog

    def occupancy(self):
        return self._occ

    def queued_prefill_tokens(self):
        return self._backlog

    def rates(self, window_s=None):
        return self.timeseries.rates(window_s or 60.0)

    def health(self):
        return engine_health(self)


def test_engine_health_scores_states_and_saturation():
    clock = FakeClock()
    idle = FakeEngine(clock)
    h = idle.health()
    assert h == {**h, "score": 1.0, "state": "ok", "state_code": 0, "enabled": True}
    assert h["saturation"]["max"] == 0.0

    saturated = FakeEngine(clock, resident=4, waiting=8, backlog=64)
    h = saturated.health()
    assert h["state"] == "ok"
    assert h["saturation"]["slots"] == 1.0 and h["saturation"]["prefill_backlog"] == 1.0
    assert h["score"] == pytest.approx(0.5)  # loaded-but-meeting-SLO floors at 0.5

    breaching = FakeEngine(clock)
    breaching.timeseries.ttft.observe(0.500)
    h = breaching.health()
    assert h["state"] == "breach" and h["state_code"] == 2
    assert h["score"] == pytest.approx(STATE_FACTORS["breach"])
    # any breaching replica scores strictly below any non-breaching one
    assert h["score"] < 0.5


def test_engine_health_payload_is_none_free_and_prometheus_clean():
    from unionml_tpu.observability import render_prometheus

    clock = FakeClock()
    engine = FakeEngine(clock)
    engine.timeseries.ttft.observe(0.500)

    def no_none(node):
        if isinstance(node, dict):
            return all(no_none(v) for v in node.values())
        if isinstance(node, (list, tuple)):
            return all(no_none(v) for v in node)
        return node is not None

    fleet = fleet_health(engine)
    assert no_none(fleet)
    text = render_prometheus({"requests_total": 0, "errors_total": 0, "fleet": fleet})
    assert "None" not in text
    assert "unionml_tpu_fleet_score" in text
    assert "unionml_tpu_fleet_state_code 2" in text


def test_fleet_health_aggregates_mean_worst_and_state():
    clock = FakeClock()

    class Fleet:
        def __init__(self, engines):
            self.batchers = tuple(engines)

    good, bad = FakeEngine(clock), FakeEngine(clock)
    bad.timeseries.ttft.observe(0.500)
    fleet = fleet_health(Fleet([good, bad]))
    assert fleet["state"] == "breach"
    assert fleet["worst_score"] == pytest.approx(STATE_FACTORS["breach"])
    assert fleet["score"] == pytest.approx((1.0 + STATE_FACTORS["breach"]) / 2)
    assert [r["replica"] for r in fleet["replicas"]] == [0, 1]
    # a telemetry-disabled engine reads as a healthy, routable replica
    class Bare:
        pass
    fleet = fleet_health(Fleet([Bare()]))
    assert fleet["replicas"][0] == {"replica": 0, "score": 1.0, "state": "ok",
                                    "state_code": 0, "enabled": False}
    assert fleet_health(None)["replicas"] == []


# ------------------------------------------------- route-around-breach policy


def test_scheduler_order_deprioritizes_breaching_replicas():
    sched = ReplicaScheduler(3)
    loads = [0.0, 5.0, 9.0]
    assert sched.order(loads)[0] == [0, 1, 2]
    # the least-loaded replica is breaching: it sinks below every healthy one
    order, _ = sched.order(loads, breaching=[True, False, False])
    assert order == [1, 2, 0]
    # everyone breaching degrades to plain least-loaded (serve, don't shed)
    order, _ = sched.order(loads, breaching=[True, True, True])
    assert order == [0, 1, 2]


def test_scheduler_affinity_head_disqualified_by_breach():
    sched = ReplicaScheduler(2, affinity_tokens=2, affinity_margin=8)
    prompt = [7, 7, 1]
    sched.note(0, prompt)  # prefix lives on replica 0
    order, affinity = sched.order([3.0, 0.0], prompt)
    assert order[0] == 0 and affinity  # warm prefix beats load within margin
    order, affinity = sched.order([3.0, 0.0], prompt, breaching=[True, False])
    assert order == [1, 0] and not affinity  # breach overrides warm affinity


def test_scheduler_cached_routing_respects_breach():
    sched = ReplicaScheduler(2, affinity_margin=8)
    cached = [128, 0]
    order, affinity = sched.order([2.0, 0.0], [1, 2, 3], cached)
    assert order[0] == 0 and affinity
    order, affinity = sched.order([2.0, 0.0], [1, 2, 3], cached, breaching=[True, False])
    assert order == [1, 0] and not affinity


# ------------------------------------------------------- serving app surface


@pytest.fixture
def app(sklearn_model):
    sklearn_model.train(hyperparameters={"max_iter": 500})
    from unionml_tpu.serving.app import ServingApp

    app = ServingApp(sklearn_model)
    app.configure_observability(trace=True, flight_recorder_size=16, access_log=False)
    return app


def _dispatch(app, method, path, body=b""):
    async def run():
        app.startup()
        return await app.server.dispatch(method, path, body)

    return asyncio.run(run())


def test_healthz_detailed_and_health_stays_bare(app):
    status, payload, ctype = _dispatch(app, "GET", "/healthz")
    assert status == 200 and ctype == "application/json"
    assert payload["ready"] is True and payload["state"] == "ok"
    assert payload["score"] == 1.0 and payload["replicas"] == []
    # /health keeps the reference's bare readiness shape — no health fields
    status, bare, _ = _dispatch(app, "GET", "/health")
    assert status == 200 and "score" not in bare and bare["ready"] is True


def test_healthz_answers_503_while_draining(app):
    _dispatch(app, "GET", "/health")  # force startup
    app.server.draining = True
    try:
        status, payload, _ = _dispatch(app, "GET", "/healthz")
        assert status == 503 and payload["ready"] is False
    finally:
        app.server.draining = False


def test_debug_fleet_endpoint(app):
    status, payload, _ = _dispatch(app, "GET", "/debug/fleet")
    assert status == 200
    assert payload["replicas"] == 0 and payload["health"]["state"] == "ok"
    assert payload["tracing"] is True and payload["exemplars"] == 0


def test_debug_requests_min_ms_and_slo_filters(app):
    _dispatch(app, "GET", "/health")
    status, payload, _ = _dispatch(app, "GET", "/debug/requests?min_ms=3600000")
    assert status == 200 and payload["completed"] == []
    status, payload, _ = _dispatch(app, "GET", "/debug/requests?min_ms=soon")
    assert status == 400
    status, payload, _ = _dispatch(app, "GET", "/debug/requests?slo=warn")
    assert status == 400 and "breach" in payload["detail"]
    # pin an exemplar by hand and fetch it through the filter
    _completed_trace(app.recorder, "exemplar-1", breach=True)
    status, payload, _ = _dispatch(app, "GET", "/debug/requests?slo=breach")
    assert status == 200
    assert [s["request_id"] for s in payload["completed"]] == ["exemplar-1"]
    status, payload, _ = _dispatch(app, "GET", "/debug/fleet")
    assert payload["exemplars"] == 1
