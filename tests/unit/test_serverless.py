"""Serverless adapter tests — modeled on the reference's
tests/unit/test_aws_lambda_handler.py: an API-Gateway event fixture driven through the
handler in-process, and an S3-event batch flow with an injected object-store client."""

import json
from pathlib import Path
from typing import List

import pandas as pd
import pytest
from sklearn.linear_model import LogisticRegression

from unionml_tpu import Dataset, Model
from unionml_tpu.serving.serverless import lambda_handler, make_batch_handler


@pytest.fixture()
def trained_model():
    dataset = Dataset(name="ds", test_size=0.2, shuffle=True, targets=["y"])
    model = Model(name="serverless_model", init=LogisticRegression, dataset=dataset)

    @dataset.reader
    def reader(n: int = 60) -> pd.DataFrame:
        rows = [{"x0": float(i % 7), "x1": float((i * 3) % 5), "y": i % 2} for i in range(n)]
        return pd.DataFrame(rows)

    @model.trainer
    def trainer(est: LogisticRegression, features: pd.DataFrame, target: pd.DataFrame) -> LogisticRegression:
        return est.fit(features, target.squeeze())

    @model.predictor
    def predictor(est: LogisticRegression, features: pd.DataFrame) -> List[float]:
        return [float(v) for v in est.predict(features)]

    model.train(hyperparameters={"max_iter": 500})
    return model


FEATURES = [{"x0": 1.0, "x1": 2.0}, {"x0": 3.0, "x1": 1.0}, {"x0": 0.0, "x1": 4.0}]


def _api_gateway_v1_event(payload: dict) -> dict:
    """Reference fixture shape: tests/unit/test_aws_lambda_handler.py:18-72."""
    return {
        "httpMethod": "POST",
        "path": "/predict",
        "headers": {"Content-Type": "application/json"},
        "body": json.dumps(payload),
        "isBase64Encoded": False,
    }


def _api_gateway_v2_event(payload: dict) -> dict:
    return {
        "rawPath": "/predict",
        "requestContext": {"http": {"method": "POST", "path": "/predict"}},
        "body": json.dumps(payload),
    }


def test_lambda_handler_predict_v1(trained_model):
    handler = lambda_handler(trained_model.serve())
    response = handler(_api_gateway_v1_event({"features": FEATURES}), None)
    assert response["statusCode"] == 200
    predictions = json.loads(response["body"])
    assert len(predictions) == len(FEATURES)
    assert all(p in (0.0, 1.0) for p in predictions)


def test_lambda_handler_predict_v2(trained_model):
    handler = lambda_handler(trained_model.serve())
    response = handler(_api_gateway_v2_event({"features": FEATURES}), None)
    assert response["statusCode"] == 200
    assert len(json.loads(response["body"])) == len(FEATURES)


def test_lambda_handler_health_and_404(trained_model):
    handler = lambda_handler(trained_model.serve())
    health = handler({"httpMethod": "GET", "path": "/health"}, None)
    assert health["statusCode"] == 200
    missing = handler({"httpMethod": "GET", "path": "/nope"}, None)
    assert missing["statusCode"] == 404


def test_lambda_handler_warm_reuse_across_invocations(trained_model):
    """Scale-to-zero contract: one container = one startup. The first
    invocation pays the (store-accelerated) cold start; the second reuses the
    warmed engine — ``startups`` must stay at 1 across invocations."""
    handler = lambda_handler(trained_model.serve())
    assert handler.stats == {"invocations": 0, "startups": 0, "cold_start_s": None}
    first = handler(_api_gateway_v1_event({"features": FEATURES}), None)
    assert first["statusCode"] == 200
    assert handler.stats["startups"] == 1
    assert handler.stats["cold_start_s"] is not None
    cold = handler.stats["cold_start_s"]
    second = handler(_api_gateway_v1_event({"features": FEATURES}), None)
    assert second["statusCode"] == 200
    assert handler.stats["invocations"] == 2
    assert handler.stats["startups"] == 1  # warm reuse: startup ran exactly once
    assert handler.stats["cold_start_s"] == cold


def test_lambda_handler_preload_moves_startup_to_init(trained_model):
    """``preload=True`` runs the startup at handler CREATION (the serverless
    init phase) so even the first invocation sees a warm engine."""
    handler = lambda_handler(trained_model.serve(), preload=True)
    assert handler.stats["startups"] == 1  # before any invocation
    assert handler.stats["invocations"] == 0
    response = handler(_api_gateway_v1_event({"features": FEATURES}), None)
    assert response["statusCode"] == 200
    assert handler.stats["startups"] == 1


def test_lambda_handler_base64_body(trained_model):
    import base64

    handler = lambda_handler(trained_model.serve())
    event = _api_gateway_v1_event({"features": FEATURES})
    event["body"] = base64.b64encode(event["body"].encode()).decode()
    event["isBase64Encoded"] = True
    response = handler(event, None)
    assert response["statusCode"] == 200


class InMemoryStore:
    """Object-store stand-in (the reference mocks boto3's s3_client the same way,
    test_aws_lambda_handler.py:141-161)."""

    def __init__(self):
        self.objects = {}

    def download_file(self, bucket: str, key: str, filename: str) -> None:
        Path(filename).write_bytes(self.objects[(bucket, key)])

    def upload_file(self, filename: str, bucket: str, key: str) -> None:
        self.objects[(bucket, key)] = Path(filename).read_bytes()


def _s3_event(bucket: str, key: str) -> dict:
    """Reference fixture shape: tests/unit/test_aws_lambda_handler.py:75-110."""
    return {"Records": [{"s3": {"bucket": {"name": bucket}, "object": {"key": key}}}]}


def test_batch_handler_s3_flow(trained_model):
    store = InMemoryStore()
    store.objects[("inbox", "uploads/features.json")] = json.dumps(FEATURES).encode()

    handler = make_batch_handler(trained_model, store)
    result = handler(_s3_event("inbox", "uploads/features.json"), None)
    assert result["statusCode"] == 200
    # the input key's directory prefix is preserved so same-named files under
    # different prefixes don't overwrite each other's predictions
    assert result["outputs"] == [{"bucket": "inbox", "key": "predictions/uploads/features.json"}]
    predictions = json.loads(store.objects[("inbox", "predictions/uploads/features.json")])
    assert len(predictions) == len(FEATURES)


def test_batch_handler_runs_feature_pipeline_once():
    """A feature_loader that only accepts a Path: the handler must not re-run
    dataset.get_features on already-loaded features (SURVEY.md §3.2 double-processing
    quirk)."""
    dataset = Dataset(name="ds", test_size=0.2, shuffle=True, targets=["y"])
    model = Model(name="once_model", init=LogisticRegression, dataset=dataset)

    @dataset.reader
    def reader(n: int = 60) -> pd.DataFrame:
        rows = [{"x0": float(i % 7), "x1": float((i * 3) % 5), "y": i % 2} for i in range(n)]
        return pd.DataFrame(rows)

    @dataset.feature_loader
    def feature_loader(features: Path) -> pd.DataFrame:
        assert isinstance(features, Path), f"feature_loader re-invoked on {type(features)}"
        return pd.DataFrame(json.loads(features.read_text()))

    @model.trainer
    def trainer(est: LogisticRegression, features: pd.DataFrame, target: pd.DataFrame) -> LogisticRegression:
        return est.fit(features, target.squeeze())

    @model.predictor
    def predictor(est: LogisticRegression, features: pd.DataFrame) -> List[float]:
        return [float(v) for v in est.predict(features)]

    model.train(hyperparameters={"max_iter": 500})

    store = InMemoryStore()
    store.objects[("inbox", "uploads/features.json")] = json.dumps(FEATURES).encode()
    handler = make_batch_handler(model, store)
    result = handler(_s3_event("inbox", "uploads/features.json"), None)
    assert result["statusCode"] == 200
    assert len(json.loads(store.objects[("inbox", "predictions/uploads/features.json")])) == len(FEATURES)


def test_batch_handler_skips_malformed_records(trained_model):
    handler = make_batch_handler(trained_model, InMemoryStore())
    result = handler({"Records": [{"s3": {}}]}, None)
    assert result == {"statusCode": 200, "outputs": []}


def test_batch_handler_ignores_own_outputs(trained_model):
    """Whole-bucket event notifications must not recurse on the handler's own
    predictions objects."""
    store = InMemoryStore()
    store.objects[("inbox", "predictions/features.json")] = json.dumps([1.0]).encode()
    handler = make_batch_handler(trained_model, store)
    result = handler(_s3_event("inbox", "predictions/features.json"), None)
    assert result == {"statusCode": 200, "outputs": []}

    # a distinct output bucket is safe: same-prefix inputs still process
    store2 = InMemoryStore()
    store2.objects[("inbox", "predictions/features.json")] = json.dumps(FEATURES).encode()
    handler2 = make_batch_handler(trained_model, store2, output_bucket="outbox")
    result2 = handler2(_s3_event("inbox", "predictions/features.json"), None)
    assert result2["outputs"] == [{"bucket": "outbox", "key": "predictions/predictions/features.json"}]


def test_batch_handler_url_encoded_keys(trained_model):
    """S3 event notifications URL-encode keys: 'daily report.csv' arrives as
    'daily+report.csv' and must be decoded before the GetObject call."""
    store = InMemoryStore()
    store.objects[("inbox", "daily report.json")] = json.dumps(FEATURES).encode()
    handler = make_batch_handler(trained_model, store)
    result = handler(_s3_event("inbox", "daily+report.json"), None)
    assert result["outputs"] == [{"bucket": "inbox", "key": "predictions/daily report.json"}]


def test_lambda_handler_echoes_request_id(trained_model):
    """The X-Request-Id contract survives the event bridge (docs/observability.md):
    inbound ids come back on success AND error responses, absent ids are minted."""
    handler = lambda_handler(trained_model.serve())
    event = _api_gateway_v1_event({"features": FEATURES})
    event["headers"]["X-Request-Id"] = "lambda-rid-1"
    response = handler(event, None)
    assert response["statusCode"] == 200
    assert response["headers"]["X-Request-Id"] == "lambda-rid-1"

    missing = handler(
        {"httpMethod": "GET", "path": "/nope", "headers": {"X-Request-Id": "lambda-rid-2"}},
        None,
    )
    assert missing["statusCode"] == 404
    assert missing["headers"]["X-Request-Id"] == "lambda-rid-2"

    minted = handler({"httpMethod": "GET", "path": "/health"}, None)
    assert len(minted["headers"]["X-Request-Id"]) == 32
