"""Replica scheduler + fleet-level overload behavior (host logic only).

The device-facing half (token-identical dp=2 x tp=2 serving, mesh slicing,
delegation) lives in tests/emulated/test_replicas.py; here stub engines pin the
pure routing/shedding logic: least-loaded order, tie-breaks, prefix affinity
(hit, hotspot fallback, LRU bound), full-fleet 429, pre-routing deadline 503,
and stats aggregation.
"""

import time

import pytest

from unionml_tpu.serving.overload import DeadlineExceeded, QueueFullError
from unionml_tpu.serving.replicas import ReplicaScheduler, ReplicaSet


class _StubEngine:
    """Duck-typed ContinuousBatcher: enough surface for the ReplicaSet."""

    def __init__(self, load=0, full=False, backlog_tokens=0):
        self._load = load
        self._backlog = backlog_tokens
        self.full = full
        self.submitted = []
        self.slots = 4
        self.shed_queue_full = 0
        self.shed_deadline = 0

    def load(self):
        # token-weighted, like the real engine: requests + normalized backlog
        return self._load + self._backlog / 512

    def queued_prefill_tokens(self):
        return self._backlog

    def occupancy(self):
        return min(self._load, self.slots), max(self._load - self.slots, 0)

    def submit(self, prompt, **kwargs):
        if self.full:
            self.shed_queue_full += 1
            raise QueueFullError("stub queue full")
        self.submitted.append(list(prompt))
        self._load += 1
        return iter(())

    def stats(self):
        resident, waiting = self.occupancy()
        return {
            "slots": self.slots,
            "resident": resident,
            "waiting": waiting,
            "shed_queue_full": self.shed_queue_full,
            "shed_deadline": self.shed_deadline,
            "decode_dispatches": 7,
            "decoded_rows": 21,
            "prefill": {"chunks": 0, "backlog_tokens": self._backlog},
        }

    def warmup(self):
        pass

    def close(self, wait=True, timeout=None):
        pass


def _set(engines, **kwargs):
    return ReplicaSet(engines=engines, **kwargs)


# ------------------------------------------------------------------ scheduler


def test_least_loaded_order_with_tie_break():
    sched = ReplicaScheduler(3)
    order, affinity = sched.order([2, 0, 1])
    assert order == [1, 2, 0] and affinity is False
    order, _ = sched.order([1, 1, 1])
    assert order == [0, 1, 2]  # ties break toward the lowest index


def test_affinity_prefers_remembered_replica_within_margin():
    sched = ReplicaScheduler(2, affinity_tokens=3, affinity_margin=2)
    prompt = [5, 6, 7, 8]
    sched.note(1, prompt)
    order, affinity = sched.order([0, 2], prompt)  # replica 1 busier, within margin
    assert order[0] == 1 and affinity is True
    # a DIFFERENT prefix has no affinity entry: plain least-loaded
    order, affinity = sched.order([0, 2], [9, 9, 9, 8])
    assert order[0] == 0 and affinity is False
    # prompts shorter than the affinity window share nothing to exploit
    assert sched.order([0, 2], [5, 6])[0][0] == 0


def test_affinity_abandons_hotspots_beyond_the_margin():
    sched = ReplicaScheduler(2, affinity_tokens=2, affinity_margin=1)
    prompt = [1, 2, 3]
    sched.note(0, prompt)
    order, affinity = sched.order([5, 0], prompt)  # 5 > 0 + margin: hotspot
    assert order[0] == 1 and affinity is False


def test_affinity_map_is_lru_bounded():
    sched = ReplicaScheduler(2, affinity_tokens=1, affinity_capacity=2)
    for token in range(5):
        sched.note(token % 2, [token, 99])
    assert sched.stats()["affinity_entries"] == 2


def test_cached_lengths_take_precedence_over_the_lru_heuristic():
    """With per-replica radix probes supplied, routing follows the ACTUAL
    cached-prefix length — even against a stale LRU entry — with the same
    hotspot margin guard; all-zero probes fall back to the LRU path."""
    sched = ReplicaScheduler(3, affinity_tokens=2, affinity_margin=2)
    prompt = [1, 2, 3, 4]
    sched.note(0, prompt)  # stale LRU memory says replica 0
    order, affinity = sched.order([1, 0, 1], prompt, cached=[0, 0, 24])
    assert order[0] == 2 and affinity is True  # replica 2 really holds the KV
    # hotspot guard: the cache-holding replica is too far above least-loaded
    order, affinity = sched.order([1, 0, 9], prompt, cached=[0, 0, 24])
    assert order == [1, 0, 2] and affinity is False
    # nothing cached anywhere: the LRU heuristic still applies
    order, affinity = sched.order([1, 0, 1], prompt, cached=[0, 0, 0])
    assert order[0] == 0 and affinity is True
    # ties on cached length break toward the less loaded replica
    order, _ = sched.order([3, 1, 2], prompt, cached=[16, 16, 0])
    assert order[0] == 1


# ------------------------------------------------------------------ replica set


def test_submit_routes_least_loaded_and_walks_past_full_replicas():
    engines = [_StubEngine(load=3), _StubEngine(load=1), _StubEngine(load=2)]
    replica_set = _set(engines)
    replica_set.submit([1, 2])
    assert engines[1].submitted == [[1, 2]]  # least loaded took it
    engines[1].full = True
    replica_set.submit([3, 4])
    assert engines[2].submitted == [[3, 4]]  # full replica fell through
    assert replica_set.stats()["scheduler"]["submitted"] == [0, 1, 1]


def test_full_fleet_sheds_queue_full():
    engines = [_StubEngine(full=True), _StubEngine(full=True)]
    replica_set = _set(engines)
    with pytest.raises(QueueFullError):
        replica_set.submit([1])
    stats = replica_set.stats()
    # one fleet-level shed on top of each engine's own attempt counter
    assert stats["shed_queue_full"] == 1 + 2


def test_expired_deadline_sheds_before_routing():
    engines = [_StubEngine()]
    replica_set = _set(engines)
    with pytest.raises(DeadlineExceeded):
        replica_set.submit([1], deadline=time.monotonic() - 0.1)
    assert engines[0].submitted == []  # never routed, no engine work spent
    assert replica_set.stats()["shed_deadline"] == 1


def test_affinity_routes_shared_prefixes_to_the_same_replica():
    engines = [_StubEngine(), _StubEngine()]
    replica_set = _set(engines, affinity_tokens=3)
    replica_set.submit([7, 8, 9, 1])  # -> replica 0 (idle tie-break)
    replica_set.submit([1, 2, 3, 4])  # -> replica 1 (least loaded)
    replica_set.submit([7, 8, 9, 2])  # shared prefix -> replica 0 despite equal load
    assert [len(e.submitted) for e in engines] == [2, 1]
    assert replica_set.stats()["scheduler"]["affinity_hits"] == 1


def test_stats_aggregates_across_replicas():
    replica_set = _set([_StubEngine(load=2), _StubEngine(load=5)])
    stats = replica_set.stats()
    assert stats["replicas"] == 2
    assert stats["slots"] == 8 and stats["resident"] == 2 + 4 and stats["waiting"] == 1
    assert stats["decode_dispatches"] == 14 and stats["decoded_rows"] == 42
    assert len(stats["per_replica"]) == 2
    loads = replica_set.replica_loads()
    assert loads[1] == {
        "replica": 1, "role": "mixed", "resident": 4, "waiting": 1, "free_slots": 0,
        "prefill_backlog_tokens": 0, "shed_queue_full": 0, "shed_deadline": 0,
    }


def test_token_weighted_load_breaks_waiter_count_ties():
    """Two replicas with EQUAL waiter counts but very different prefill
    backlogs must not tie: the token-weighted load() ranks the shallow
    backlog first (mixed prompt lengths route sensibly)."""
    engines = [_StubEngine(load=1, backlog_tokens=8192), _StubEngine(load=1, backlog_tokens=16)]
    replica_set = _set(engines)
    replica_set.submit([1, 2])
    assert engines[1].submitted == [[1, 2]]  # deep-backlog replica avoided
    assert replica_set.queued_prefill_tokens() == 8192 + 16
    stats = replica_set.stats()
    assert stats["prefill_backlog_tokens"] == 8192 + 16


def test_affinity_hotspot_fallback_ranks_on_token_weighted_load():
    """The affinity-fallback path uses the SAME token-weighted loads as the
    primary ranking: when the remembered replica is a hotspot, the fallback
    must pick the replica with the shallow prefill backlog even though waiter
    counts tie — a count-based fallback would tie-break to index 0 and land
    on the deep backlog."""
    sched = ReplicaScheduler(3, affinity_tokens=2, affinity_margin=1)
    prompt = [4, 5, 6]
    sched.note(2, prompt)  # affinity remembers replica 2
    # replica 2 is now a hotspot (load 5 > min + margin); replicas 0 and 1
    # tie on request count but 0 has a deep token backlog (load 1 + 8192/512)
    loads = [1 + 8192 / 512, 1 + 16 / 512, 5]
    order, affinity = sched.order(loads, prompt)
    assert affinity is False  # hotspot abandoned
    assert order[0] == 1  # shallow backlog wins, not index order


def test_replica_set_needs_exactly_one_source():
    with pytest.raises(ValueError):
        ReplicaSet()
    with pytest.raises(ValueError):
        ReplicaSet([object()], engines=[_StubEngine()])
