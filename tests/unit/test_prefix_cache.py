"""Radix prefix cache correctness.

Two rings: (1) the host-side :class:`RadixPrefixCache` tree itself —
insert/match/split, block refcounts, LRU eviction, sub-block (copy-on-write)
matching; (2) the engine integration — cached-prefix admissions must be
BIT-IDENTICAL to cold prefills (the same bar PR 4 held for chunked vs
monolithic), eviction under pool pressure must never deadlock admission, and
with the cache disabled the engine's stats carry no trace of it.
"""

import threading

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from unionml_tpu.models import GenerationConfig, Generator, Llama, LlamaConfig
from unionml_tpu.serving import ContinuousBatcher
from unionml_tpu.serving.prefix_cache import RadixPrefixCache


@pytest.fixture(scope="module")
def tiny_gen():
    config = LlamaConfig.tiny(
        vocab_size=97, dim=64, n_layers=2, n_heads=4, n_kv_heads=2, hidden_dim=128,
        dtype=jnp.float32, param_dtype=jnp.float32,
    )
    module = Llama(config)
    params = module.init(jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))["params"]
    return module, params


def _sequential_expected(module, params, cfg, prompts, prefix_tokens=None):
    gen = Generator(module, params, cfg)
    prefix = gen.cache_prefix(prefix_tokens) if prefix_tokens else None
    expected = []
    for p in prompts:
        row = gen([p], prefix=prefix)[0] if prefix is not None else gen([p])[0]
        if cfg.eos_id is not None:
            hits = np.nonzero(row == cfg.eos_id)[0]
            if hits.size:
                row = row[: int(hits[0]) + 1]
        expected.append(list(row))
    return expected


def _drain(stream):
    return [int(t) for chunk in stream for t in np.asarray(chunk).ravel()]


# --------------------------------------------------------------------- tree ring


def test_tree_insert_match_roundtrip():
    tree = RadixPrefixCache(4)
    tree.insert(list(range(8)), [10, 11])
    m, blocks = tree.match(list(range(8)) + [99])
    assert m == 8 and blocks == [10, 11]
    # a shorter probe matches a prefix of the run (sub-block: CoW territory)
    m, blocks = tree.match(list(range(6)))
    assert m == 6 and blocks == [10, 11]  # ceil(6/4) = 2 blocks, last partial
    assert tree.match_len(list(range(5))) == 5
    # disjoint prompt: no match
    assert tree.match([50, 51, 52]) == (0, [])


def test_tree_split_on_divergence_keeps_shared_blocks():
    tree = RadixPrefixCache(4)
    tree.insert([1, 2, 3, 4, 5, 6, 7, 8], [10, 11])
    # diverges in the SECOND block: the first stays shared, the edge splits
    kept = tree.insert([1, 2, 3, 4, 9, 9, 9, 9], [20, 21])
    assert kept == 1  # block 20 duplicated the cached [1,2,3,4] run; 21 consumed
    assert tree.match([1, 2, 3, 4, 5, 6, 7, 8]) == (8, [10, 11])
    assert tree.match([1, 2, 3, 4, 9, 9, 9, 9]) == (8, [10, 21])
    assert tree.nodes() == 3 and tree.cached_blocks() == 3
    # mid-block divergence against a sibling still yields the partial tail
    m, blocks = tree.match([1, 2, 3, 4, 9, 9, 0, 0])
    assert m == 6 and blocks == [10, 21]


def test_tree_refcounts_block_eviction():
    tree = RadixPrefixCache(4)
    tree.insert([1, 2, 3, 4], [10])
    tree.insert([5, 6, 7, 8], [20])
    m, blocks = tree.match([1, 2, 3, 4], pin=True)
    assert tree.pinned_blocks() == 1
    freed = tree.evict(8)
    assert freed == [20] and tree.evictions == 1  # the pinned run survives
    tree.release(blocks)
    assert tree.pinned_blocks() == 0
    assert sorted(tree.evict(8)) == [10]


def test_tree_lru_eviction_order_and_pinned_ancestor_shield():
    tree = RadixPrefixCache(4)
    tree.insert([1, 2, 3, 4, 5, 6, 7, 8], [10, 11])
    tree.insert([1, 2, 3, 4, 9, 9, 9, 9], [20, 21])  # splits; parent holds [10]
    tree.match([1, 2, 3, 4, 5, 6, 7, 8])  # refresh the [11] leaf's recency
    freed = tree.evict(1)
    assert freed == [21]  # the stale leaf goes first
    # pin the remaining leaf: its ancestor chain is shielded
    _, pinned = tree.match([1, 2, 3, 4, 5, 6, 7, 8], pin=True)
    assert tree.evictable_blocks() == 0
    assert tree.evict(8) == []
    tree.release(pinned)
    assert tree.evictable_blocks() == 2
    assert sorted(tree.evict(8)) == [10, 11]


def test_tree_insert_alignment_guard():
    tree = RadixPrefixCache(4)
    with pytest.raises(ValueError, match="block-aligned"):
        tree.insert([1, 2, 3], [10])


# ------------------------------------------------------------------- engine ring


PROMPTS_SHARED = [list(range(1, 21)) + [70 + i] for i in range(4)]


def test_cached_prefix_streams_match_cold_and_sequential(tiny_gen):
    """The headline contract: warm (cache-hit) streams == cold (first-visit)
    streams == sequential Generator runs, token for token."""
    module, params = tiny_gen
    cfg = GenerationConfig(max_new_tokens=10, temperature=0.0, prompt_buckets=(32,))
    expected = _sequential_expected(module, params, cfg, PROMPTS_SHARED)

    batcher = ContinuousBatcher(
        Generator(module, params, cfg), slots=2, decode_chunk=4,
        block_size=8, admit_chunk=8, prefix_cache=True,
    )
    try:
        results = [_drain(batcher.submit(p)) for p in PROMPTS_SHARED]
        assert results == expected
        stats = batcher.stats()["prefix_cache"]
        assert stats["hits"] == len(PROMPTS_SHARED) - 1  # all but the first
        assert stats["misses"] == 1
        # decode-side insertion publishes the first stream's prompt+generated
        # run, so later prompts match their WHOLE 20-token shared prefix (the
        # partial third block rides CoW), not just the 2 fully-shared blocks
        assert stats["tokens_avoided"] == 20 * (len(PROMPTS_SHARED) - 1)
        # a finished prompt's own full sequence is cached: the probe caps at
        # total-1 (the last token always prefills)
        assert batcher.cached_prefix_tokens(PROMPTS_SHARED[0]) == len(PROMPTS_SHARED[0]) - 1
    finally:
        batcher.close()


@pytest.mark.slow  # ~10s; thread-contended hits are re-pinned by the emulated
# tp=2/dp=2 ring, and the sequential identity test above stays in tier-1
def test_cached_prefix_concurrent_submissions(tiny_gen):
    """Hits under thread contention: concurrent warm submissions race the
    tree's pins/inserts through the engine lock and stay exact."""
    module, params = tiny_gen
    cfg = GenerationConfig(max_new_tokens=8, temperature=0.0, prompt_buckets=(32,))
    expected = _sequential_expected(module, params, cfg, PROMPTS_SHARED)

    batcher = ContinuousBatcher(
        Generator(module, params, cfg), slots=len(PROMPTS_SHARED), decode_chunk=3,
        block_size=8, admit_chunk=8, max_admissions=2, prefix_cache=True,
    )
    try:
        warm = _drain(batcher.submit(PROMPTS_SHARED[0]))  # publish the prefix
        assert warm == expected[0]
        results = [None] * len(PROMPTS_SHARED)

        def worker(i):
            results[i] = _drain(batcher.submit(PROMPTS_SHARED[i]))

        threads = [threading.Thread(target=worker, args=(i,)) for i in range(len(PROMPTS_SHARED))]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=120)
        assert results == expected
        assert batcher.stats()["prefix_cache"]["hits"] >= len(PROMPTS_SHARED)
    finally:
        batcher.close()


def test_cow_divergence_inside_shared_tail_block(tiny_gen):
    """A prompt diverging mid-block reuses the partially shared tail block via
    copy-on-write (gathered into its private copy) — counted, and exact."""
    module, params = tiny_gen
    cfg = GenerationConfig(max_new_tokens=8, temperature=0.0, prompt_buckets=(32,))
    long_a = list(range(1, 28))                       # caches 3 full blocks (24 tokens)
    long_b = list(range(1, 21)) + [90, 91, 92]        # shares 20: mid-block divergence
    expected = _sequential_expected(module, params, cfg, [long_a, long_b])

    # no admit_chunk: cache hits still chunk (at block_size) — the cache works
    # on engines that never enabled stall-free admission
    batcher = ContinuousBatcher(
        Generator(module, params, cfg), slots=2, decode_chunk=3,
        block_size=8, prefix_cache=True,
    )
    try:
        results = [_drain(batcher.submit(p)) for p in (long_a, long_b)]
        assert results == expected
        stats = batcher.stats()["prefix_cache"]
        assert stats["cow_copies"] == 1
        assert stats["tokens_avoided"] == 20
    finally:
        batcher.close()


def test_static_prefix_composes_and_tail_is_cached(tiny_gen):
    """With a configured shared prefix, the radix key covers (prefix + prompt):
    matches extend past the static pages into per-request prompts, the
    prefix's partial tail block is cached like any run (the satellite fix),
    and the dropped-tail count is surfaced in stats."""
    module, params = tiny_gen
    cfg = GenerationConfig(max_new_tokens=8, temperature=0.0, prompt_buckets=(32,))
    prefix_tokens = list(range(1, 12))  # 11 tokens: 1 full block of 8 + 3-token tail
    suffixes = [
        [60, 61, 62, 63, 64, 65, 66, 67, 68, 69],
        [60, 61, 62, 63, 64, 65, 66, 67, 68, 70],
    ]
    expected = _sequential_expected(module, params, cfg, suffixes, prefix_tokens=prefix_tokens)

    gen = Generator(module, params, cfg)
    batcher = ContinuousBatcher(
        gen, slots=2, decode_chunk=3, prefix=gen.cache_prefix(prefix_tokens),
        block_size=8, admit_chunk=8, prefix_cache=True,
    )
    try:
        results = [_drain(batcher.submit(s)) for s in suffixes]
        assert results == expected
        stats = batcher.stats()
        assert stats["kv_blocks"]["shared_prefix_tail_tokens"] == 3
        assert stats["prefix_cache"]["hits"] == 1  # second suffix rides the first's blocks
        assert stats["prefix_cache"]["tokens_avoided"] > 0
    finally:
        batcher.close()


def test_eviction_under_pool_pressure_never_deadlocks(tiny_gen):
    """A minimum-size pool fills with cached runs; later admissions must evict
    idle cache instead of deadlocking (the allocator-exhaustion contract)."""
    module, params = tiny_gen
    cfg = GenerationConfig(max_new_tokens=8, temperature=0.0, prompt_buckets=(16,))
    gen = Generator(module, params, cfg)
    probe = ContinuousBatcher(gen, slots=2, decode_chunk=3, block_size=8, prefix_cache=True)
    min_pool = probe.max_blocks
    probe.close()
    prompts = [list(range(i, i + 9)) for i in range(1, 60, 10)]
    expected = _sequential_expected(module, params, cfg, prompts)

    batcher = ContinuousBatcher(
        gen, slots=2, decode_chunk=3, block_size=8, pool_blocks=min_pool,
        admit_chunk=8, prefix_cache=True,
    )
    try:
        results = [_drain(batcher.submit(p)) for p in prompts]
        assert results == expected
        assert batcher.stats()["prefix_cache"]["evictions"] > 0
    finally:
        batcher.close()


def test_preemption_resume_rides_its_own_cached_prefix(tiny_gen):
    """Pool exhaustion preempts the youngest resident; its resume prompt
    (original + echo) re-matches the blocks its own admission published, and
    the stream stays exact end to end."""
    module, params = tiny_gen
    cfg = GenerationConfig(max_new_tokens=16, temperature=0.0, prompt_buckets=(16,))
    long_prompts = [list(range(1, 15)), list(range(40, 54))]
    expected = _sequential_expected(module, params, cfg, long_prompts)

    gen = Generator(module, params, cfg)
    probe = ContinuousBatcher(gen, slots=2, decode_chunk=8, block_size=8, prefix_cache=True)
    pool = 2 * probe._blocks_initial(long_prompts[0], cfg.max_new_tokens)
    assert pool < 2 * probe._blocks_lifetime(long_prompts[0], cfg.max_new_tokens)
    probe.close()
    batcher = ContinuousBatcher(
        gen, slots=2, decode_chunk=8, block_size=8, pool_blocks=pool,
        admit_chunk=8, prefix_cache=True,
    )
    try:
        results = [None] * 2

        def worker(i):
            results[i] = _drain(batcher.submit(long_prompts[i]))

        threads = [threading.Thread(target=worker, args=(i,)) for i in range(2)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=180)
        assert results == expected
        stats = batcher.stats()
        assert stats["kv_blocks"]["preemptions"] >= 1
        # the resume re-used its own published prefix: at least one hit
        assert stats["prefix_cache"]["hits"] >= 1
    finally:
        batcher.close()


@pytest.mark.slow  # ~8s; pin release also rides every finish/preempt path the
# tier-1 identity and eviction tests exercise
def test_cancel_mid_stream_releases_pins(tiny_gen):
    module, params = tiny_gen
    cfg = GenerationConfig(max_new_tokens=12, temperature=0.0, prompt_buckets=(32,))
    batcher = ContinuousBatcher(
        Generator(module, params, cfg), slots=2, decode_chunk=2,
        block_size=8, admit_chunk=8, prefix_cache=True,
    )
    try:
        _drain(batcher.submit(PROMPTS_SHARED[0]))  # publish
        stream = batcher.submit(PROMPTS_SHARED[1])
        next(iter(stream))
        stream.close()
        # pins must drain back to the permanent zero once the engine reaps
        deadline = [p for p in range(200)]
        for _ in deadline:
            with batcher._lock:
                clear = all(not s.pins for s in batcher._sessions.values())
            if clear and batcher.stats()["prefix_cache"]["pinned_blocks"] == 0:
                break
            import time
            time.sleep(0.05)
        assert batcher.stats()["prefix_cache"]["pinned_blocks"] == 0
        # the engine keeps serving exact streams afterwards
        expected = _sequential_expected(module, params, cfg, [PROMPTS_SHARED[2]])
        assert _drain(batcher.submit(PROMPTS_SHARED[2])) == expected[0]
    finally:
        batcher.close()


@pytest.mark.slow  # ~5s of warmup compiles; the reset path itself is host-only
def test_warmup_resets_cache_to_clean_tree(tiny_gen):
    module, params = tiny_gen
    cfg = GenerationConfig(max_new_tokens=4, temperature=0.0, prompt_buckets=(16,))
    batcher = ContinuousBatcher(
        Generator(module, params, cfg), slots=2, decode_chunk=2,
        block_size=8, admit_chunk=8, prefix_cache=True,
    )
    try:
        batcher.warmup()
        stats = batcher.stats()["prefix_cache"]
        assert stats["hits"] == 0 and stats["misses"] == 0
        assert stats["cached_blocks"] == 0 and stats["nodes"] == 0
        # pool fully recovered: nothing leaked into the tree
        assert batcher.stats()["kv_blocks"]["used"] == 0
    finally:
        batcher.close()


@pytest.mark.slow  # ~8s; off-mode paged behavior is already pinned by the whole
# pre-cache test_continuous ring — this adds only the no-new-stats assertion
def test_disabled_cache_leaves_engine_and_stats_untouched(tiny_gen):
    module, params = tiny_gen
    cfg = GenerationConfig(max_new_tokens=6, temperature=0.0, prompt_buckets=(16,))
    batcher = ContinuousBatcher(Generator(module, params, cfg), slots=2, decode_chunk=3, block_size=8)
    try:
        expected = _sequential_expected(module, params, cfg, [[5, 6, 7]])
        assert _drain(batcher.submit([5, 6, 7])) == expected[0]
        stats = batcher.stats()
        assert "prefix_cache" not in stats
        assert batcher.cached_prefix_tokens([5, 6, 7]) == 0
        assert batcher._radix is None
    finally:
        batcher.close()


def test_prefix_cache_knob_validation(tiny_gen, monkeypatch):
    module, params = tiny_gen
    cfg = GenerationConfig(max_new_tokens=4, temperature=0.0, prompt_buckets=(16,))
    # explicit True without paged mode is a usage error
    with pytest.raises(ValueError, match="paged"):
        ContinuousBatcher(Generator(module, params, cfg), slots=1, prefix_cache=True)
    # the env export enables paged engines and is ignored (warn) on dense ones
    monkeypatch.setenv("UNIONML_TPU_PREFIX_CACHE", "1")
    dense = ContinuousBatcher(Generator(module, params, cfg), slots=1)
    assert dense._radix is None
    dense.close()
    paged = ContinuousBatcher(Generator(module, params, cfg), slots=1, block_size=8)
    assert paged._radix is not None
    paged.close()
    monkeypatch.setenv("UNIONML_TPU_PREFIX_CACHE", "0")
    off = ContinuousBatcher(Generator(module, params, cfg), slots=1, block_size=8)
    assert off._radix is None
    off.close()


def test_prefix_cache_rejects_tokenless_prefix_and_draft(tiny_gen):
    import dataclasses

    from unionml_tpu.models.generate import DraftSpec, PrefixCache

    module, params = tiny_gen
    cfg = GenerationConfig(max_new_tokens=4, temperature=0.0, prompt_buckets=(16,))
    gen = Generator(module, params, cfg)
    real = gen.cache_prefix([1, 2, 3, 4])
    handbuilt = PrefixCache(layers=real.layers, length=real.length, tokens=None)
    with pytest.raises(ValueError, match="token ids"):
        ContinuousBatcher(
            Generator(module, params, cfg), slots=1, block_size=8,
            prefix=handbuilt, prefix_cache=True,
        )
    spec_cfg = dataclasses.replace(
        cfg, draft=DraftSpec(module=module, params=params, gamma=2)
    )
    with pytest.raises(ValueError, match="speculative"):
        ContinuousBatcher(
            Generator(module, params, spec_cfg), slots=1, block_size=8, prefix_cache=True
        )
