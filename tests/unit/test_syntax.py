"""Fast static-analysis gate for the whole tree: syntax + tpu-lint.

A SyntaxError in a module that tests import (docs/build.py had one — an
f-string expression containing a backslash, illegal before Python 3.12) breaks
pytest COLLECTION of the importing test file: the suite reports a collection
error and silently stops running every test in that file. This gate compiles
every source file directly, so a syntax regression fails THIS test loudly with
the offending file and line instead.

The second gate runs tpu-lint (:mod:`unionml_tpu.analysis`) over the package:
the tree must stay clean — real findings get fixed, justified exceptions carry
an inline ``# tpu-lint: disable=RULE`` with a why-comment — so the analyzer is
a permanent CI gate, not a demo. A time-budget assertion keeps the whole gate
inside the tier-1 envelope.

Equivalent CLI gates (usable as pre-commit / CI steps on their own):
``python -m compileall -q unionml_tpu docs tests`` and
``unionml-tpu lint unionml_tpu``.
"""

import compileall
import re
import time
from pathlib import Path

REPO = Path(__file__).resolve().parents[2]

#: trees whose .py files must all parse; benchmarks and templates included —
#: templates are exec'd by the framework-app tests, benchmarks by operators
_TREES = ("unionml_tpu", "docs", "tests", "benchmarks")


def test_every_source_file_compiles():
    failures = []
    for tree in _TREES:
        root = REPO / tree
        if not root.exists():
            continue
        # quiet=1 still prints per-file errors to stdout (pytest captures and
        # shows them on failure); rx excludes nothing — the whole tree gates
        ok = compileall.compile_dir(
            str(root), quiet=1, force=False, rx=re.compile(r"/\.git/")
        )
        if not ok:
            failures.append(tree)
    assert not failures, (
        f"syntax errors under {failures}; run `python -m compileall -q "
        + " ".join(_TREES)
        + "` for details"
    )


def test_tree_is_lint_clean():
    """The package passes tpu-lint with zero active findings (fixed, or
    suppressed inline with a justification) — and fast enough to stay a
    tier-1 gate, cold AND incremental."""
    from unionml_tpu.analysis import clear_index_cache, render_text, run_lint

    clear_index_cache()  # measure the true cold path even if an earlier test linted
    start = time.perf_counter()
    result = run_lint([REPO / "unionml_tpu"])
    elapsed = time.perf_counter() - start
    assert result.clean, "tpu-lint findings (fix, or suppress with justification):\n" + render_text(result)
    assert result.files > 50, "lint walked suspiciously few files — path wiring broke"
    # perf budget: the gate must not eat the tier-1 envelope. The cold run
    # pays parse + project-index build + every rule check; the budget leaves
    # headroom for tree growth without masking an accidentally quadratic rule
    # (7s: the workloads subsystem + TPU014 put the ~100-file cold pass at
    # ~4.6s ambient on this machine — 5s flaked under concurrent test load;
    # the WARM assertion below is the contract that keeps the gate cheap)
    assert elapsed < 7.0, f"cold lint run took {elapsed:.1f}s (> 7s budget)"
    # incremental contract: the content-hash index cache makes a warm run
    # skip parsing and per-file re-checks entirely — this is what keeps the
    # gate cheap as the tree grows (and what bench_lint.py tracks as
    # cold-vs-warm)
    start = time.perf_counter()
    warm = run_lint([REPO / "unionml_tpu"])
    warm_elapsed = time.perf_counter() - start
    assert warm.clean
    assert warm.index_stats["misses"] == 0, "warm run rebuilt summaries — cache invalidation broke"
    assert warm_elapsed < 2.0, f"warm (incremental) lint took {warm_elapsed:.1f}s (> 2s budget)"


def test_lint_gate_fails_on_seeded_violation(tmp_path):
    """The gate actually gates: a seeded violation exits non-zero through the
    same entry points the CI/CLI use."""
    from unionml_tpu.analysis import run_lint
    from unionml_tpu.analysis.engine import main as lint_main

    seeded = tmp_path / "seeded.py"
    seeded.write_text("import os\nWORKERS = int(os.environ['WORKERS'])\n")
    assert not run_lint([seeded]).clean
    assert lint_main([str(seeded)]) == 1


def test_lint_gate_fails_on_seeded_lock_cycle(tmp_path):
    """The whole-program side of the gate gates too: an actual two-lock cycle
    seeded across two modules must fail through the same entry points — this
    is the deadlock class the per-file rules structurally cannot see."""
    from unionml_tpu.analysis import run_lint
    from unionml_tpu.analysis.engine import main as lint_main

    pkg = tmp_path / "seededpkg"
    pkg.mkdir()
    (pkg / "__init__.py").write_text("")
    (pkg / "fleet.py").write_text(
        "import threading\n"
        "from seededpkg.engine import Engine\n\n\n"
        "class Fleet:\n"
        "    def __init__(self):\n"
        "        self._scale_lock = threading.Lock()\n"
        "        self._engine = Engine()\n\n"
        "    def scale(self):\n"
        "        with self._scale_lock:\n"
        "            self._engine.drain(self)\n"
    )
    (pkg / "engine.py").write_text(
        "import threading\n"
        "import seededpkg.fleet\n\n\n"
        "class Engine:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n\n"
        "    def drain(self, fleet: seededpkg.fleet.Fleet):\n"
        "        with self._lock:\n"
        "            fleet.scale()\n"
    )
    result = run_lint([pkg])
    assert not result.clean
    assert [finding.rule for finding in result.findings] == ["TPU010"]
    assert "lock-order cycle" in result.findings[0].message
    assert lint_main([str(pkg)]) == 1


def test_lint_gate_fails_on_seeded_flow_violations(tmp_path):
    """The exception-path flow rules gate too: one seeded fixture per rule
    (TPU016 leak-on-exception, TPU017 charge-without-refund, TPU018
    lock-held-across-yield, TPU019 unreleased-on-early-return) must fail
    through the same entry points the CI/CLI use — these are the classes the
    syntactic rules structurally cannot see without a CFG."""
    from unionml_tpu.analysis import run_lint
    from unionml_tpu.analysis.engine import main as lint_main

    pkg = tmp_path / "flowpkg"
    pkg.mkdir()
    (pkg / "__init__.py").write_text("")
    (pkg / "leak.py").write_text(  # TPU016: request() raises -> conn leaks
        "from http.client import HTTPConnection\n\n\n"
        "def fetch(host, payload):\n"
        "    conn = HTTPConnection(host)\n"
        '    conn.request("POST", "/step", payload)\n'
        "    body = conn.getresponse().read()\n"
        "    conn.close()\n"
        "    return body\n"
    )
    (pkg / "charge.py").write_text(  # TPU017: charged, then an unguarded raise path
        "def submit(registry, tenant, grammar, compile_grammar):\n"
        "    retry_after = registry.try_admit(tenant)\n"
        "    if retry_after is not None:\n"
        '        raise RuntimeError("throttled")\n'
        "    compile_grammar(grammar)\n"
        "    return True\n"
    )
    (pkg / "stream.py").write_text(  # TPU018: consumer stalls -> lock held forever
        "import threading\n\n\n"
        "class Streamer:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n\n"
        "    def stream(self, chunks):\n"
        "        with self._lock:\n"
        "            for chunk in chunks:\n"
        "                yield chunk\n"
    )
    (pkg / "early.py").write_text(  # TPU019: early return skips the close
        "def read_config(path, strict):\n"
        "    handle = open(path)\n"
        "    if strict:\n"
        "        return None\n"
        "    handle.close()\n"
        "    return True\n"
    )
    result = run_lint([pkg])
    assert not result.clean
    seeded = {finding.rule for finding in result.findings}
    assert {"TPU016", "TPU017", "TPU018", "TPU019"} <= seeded
    assert lint_main([str(pkg)]) == 1
