"""Fast static-analysis gate for the whole tree: syntax + tpu-lint.

A SyntaxError in a module that tests import (docs/build.py had one — an
f-string expression containing a backslash, illegal before Python 3.12) breaks
pytest COLLECTION of the importing test file: the suite reports a collection
error and silently stops running every test in that file. This gate compiles
every source file directly, so a syntax regression fails THIS test loudly with
the offending file and line instead.

The second gate runs tpu-lint (:mod:`unionml_tpu.analysis`) over the package:
the tree must stay clean — real findings get fixed, justified exceptions carry
an inline ``# tpu-lint: disable=RULE`` with a why-comment — so the analyzer is
a permanent CI gate, not a demo. A time-budget assertion keeps the whole gate
inside the tier-1 envelope.

Equivalent CLI gates (usable as pre-commit / CI steps on their own):
``python -m compileall -q unionml_tpu docs tests`` and
``unionml-tpu lint unionml_tpu``.
"""

import compileall
import re
import time
from pathlib import Path

REPO = Path(__file__).resolve().parents[2]

#: trees whose .py files must all parse; benchmarks and templates included —
#: templates are exec'd by the framework-app tests, benchmarks by operators
_TREES = ("unionml_tpu", "docs", "tests", "benchmarks")


def test_every_source_file_compiles():
    failures = []
    for tree in _TREES:
        root = REPO / tree
        if not root.exists():
            continue
        # quiet=1 still prints per-file errors to stdout (pytest captures and
        # shows them on failure); rx excludes nothing — the whole tree gates
        ok = compileall.compile_dir(
            str(root), quiet=1, force=False, rx=re.compile(r"/\.git/")
        )
        if not ok:
            failures.append(tree)
    assert not failures, (
        f"syntax errors under {failures}; run `python -m compileall -q "
        + " ".join(_TREES)
        + "` for details"
    )


def test_tree_is_lint_clean():
    """The package passes tpu-lint with zero active findings (fixed, or
    suppressed inline with a justification) — and fast enough to stay a
    tier-1 gate."""
    from unionml_tpu.analysis import render_text, run_lint

    start = time.perf_counter()
    result = run_lint([REPO / "unionml_tpu"])
    elapsed = time.perf_counter() - start
    assert result.clean, "tpu-lint findings (fix, or suppress with justification):\n" + render_text(result)
    assert result.files > 50, "lint walked suspiciously few files — path wiring broke"
    # perf budget: the gate must not eat the tier-1 envelope. ~0.5s today on
    # this host; 5s leaves headroom for tree growth without masking an
    # accidentally quadratic rule
    assert elapsed < 5.0, f"lint run took {elapsed:.1f}s (> 5s budget)"


def test_lint_gate_fails_on_seeded_violation(tmp_path):
    """The gate actually gates: a seeded violation exits non-zero through the
    same entry points the CI/CLI use."""
    from unionml_tpu.analysis import run_lint
    from unionml_tpu.analysis.engine import main as lint_main

    seeded = tmp_path / "seeded.py"
    seeded.write_text("import os\nWORKERS = int(os.environ['WORKERS'])\n")
    assert not run_lint([seeded]).clean
    assert lint_main([str(seeded)]) == 1
