"""Fast syntax gate for the whole tree.

A SyntaxError in a module that tests import (docs/build.py had one — an
f-string expression containing a backslash, illegal before Python 3.12) breaks
pytest COLLECTION of the importing test file: the suite reports a collection
error and silently stops running every test in that file. This gate compiles
every source file directly, so a syntax regression fails THIS test loudly with
the offending file and line instead.

Equivalent CLI gate (usable as a pre-commit / CI step on its own):
``python -m compileall -q unionml_tpu docs tests``.
"""

import compileall
import re
from pathlib import Path

REPO = Path(__file__).resolve().parents[2]

#: trees whose .py files must all parse; benchmarks and templates included —
#: templates are exec'd by the framework-app tests, benchmarks by operators
_TREES = ("unionml_tpu", "docs", "tests", "benchmarks")


def test_every_source_file_compiles():
    failures = []
    for tree in _TREES:
        root = REPO / tree
        if not root.exists():
            continue
        # quiet=1 still prints per-file errors to stdout (pytest captures and
        # shows them on failure); rx excludes nothing — the whole tree gates
        ok = compileall.compile_dir(
            str(root), quiet=1, force=False, rx=re.compile(r"/\.git/")
        )
        if not ok:
            failures.append(tree)
    assert not failures, (
        f"syntax errors under {failures}; run `python -m compileall -q "
        + " ".join(_TREES)
        + "` for details"
    )
