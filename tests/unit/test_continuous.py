"""Continuous batching correctness.

Oracle: each concurrent stream's tokens must equal a sequential
``Generator.__call__([prompt])`` run (greedy, f32) — resident rows are
independent under the cache contract, so sharing decode dispatches must be
invisible in the output. Also pins slot reuse under contention, eos/budget
exits, and engine-failure isolation.
"""

import threading

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from unionml_tpu.models import GenerationConfig, Generator, Llama, LlamaConfig
from unionml_tpu.serving import ContinuousBatcher


@pytest.fixture(scope="module")
def tiny_gen():
    config = LlamaConfig.tiny(
        vocab_size=97, dim=64, n_layers=2, n_heads=4, n_kv_heads=2, hidden_dim=128,
        dtype=jnp.float32, param_dtype=jnp.float32,
    )
    module = Llama(config)
    params = module.init(jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))["params"]
    return module, params


PROMPTS = [[3, 14, 15, 92, 6], [27, 1], [8, 2, 8, 1, 8, 2, 8], [44, 9], [61, 5, 2], [7]]


def _sequential_expected(module, params, cfg, prompts):
    """Per-prompt sequential decode, truncated at the first eos (the stream
    contract: emit the eos, then end)."""
    gen = Generator(module, params, cfg)
    expected = []
    for p in prompts:
        row = gen([p])[0]
        if cfg.eos_id is not None:
            hits = np.nonzero(row == cfg.eos_id)[0]
            if hits.size:
                row = row[: int(hits[0]) + 1]
        expected.append(list(row))
    return expected


def _drain(stream):
    return [int(t) for chunk in stream for t in np.asarray(chunk).ravel()]


def test_concurrent_streams_match_sequential(tiny_gen):
    module, params = tiny_gen
    cfg = GenerationConfig(max_new_tokens=12, temperature=0.0, prompt_buckets=(16,))
    expected = _sequential_expected(module, params, cfg, PROMPTS)

    batcher = ContinuousBatcher(Generator(module, params, cfg), slots=len(PROMPTS), decode_chunk=4)
    try:
        results = [None] * len(PROMPTS)

        def worker(i):
            results[i] = _drain(batcher.submit(PROMPTS[i]))

        threads = [threading.Thread(target=worker, args=(i,)) for i in range(len(PROMPTS))]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=120)
        assert results == expected
        # concurrency actually shared dispatches: far fewer than per-request loops
        assert batcher.decoded_rows > batcher.decode_dispatches
    finally:
        batcher.close()


def test_slot_contention_queues_and_reuses_slots(tiny_gen):
    """More requests than slots: the overflow waits for a free slot and still
    produces exact tokens — slot rows are fully overwritten on admission."""
    module, params = tiny_gen
    cfg = GenerationConfig(max_new_tokens=8, temperature=0.0, prompt_buckets=(16,))
    expected = _sequential_expected(module, params, cfg, PROMPTS)

    batcher = ContinuousBatcher(Generator(module, params, cfg), slots=2, decode_chunk=3)
    try:
        results = [None] * len(PROMPTS)

        def worker(i):
            results[i] = _drain(batcher.submit(PROMPTS[i]))

        threads = [threading.Thread(target=worker, args=(i,)) for i in range(len(PROMPTS))]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=180)
        assert results == expected
    finally:
        batcher.close()


def test_eos_frees_slot_early(tiny_gen):
    """A row hitting eos leaves at the next chunk boundary; its tokens end with
    the eos and its slot admits the next waiter."""
    module, params = tiny_gen
    free = Generator(
        module, params, GenerationConfig(max_new_tokens=16, temperature=0.0, prompt_buckets=(16,))
    )(PROMPTS[:1])
    eos = int(free[0][3])  # an id the sequence actually emits mid-stream
    cfg = GenerationConfig(
        max_new_tokens=16, temperature=0.0, prompt_buckets=(16,), eos_id=eos, pad_id=0
    )
    expected = _sequential_expected(module, params, cfg, PROMPTS[:3])

    batcher = ContinuousBatcher(Generator(module, params, cfg), slots=1, decode_chunk=4)
    try:
        # slots=1 forces strict sequencing through one slot; eos/budget exits
        # must free it or the later submissions would hang
        results = [_drain(batcher.submit(p)) for p in PROMPTS[:3]]
        assert results == expected
        assert results[0][-1] == eos
    finally:
        batcher.close()


def test_oversized_prompt_fails_only_its_stream(tiny_gen):
    module, params = tiny_gen
    cfg = GenerationConfig(max_new_tokens=6, temperature=0.0, prompt_buckets=(8,))
    batcher = ContinuousBatcher(Generator(module, params, cfg), slots=2, decode_chunk=2)
    try:
        bad = batcher.submit(list(range(1, 80)))  # bucket 128 >> cache_len
        with pytest.raises(ValueError, match="cache_len"):
            _drain(bad)
        good = _drain(batcher.submit(PROMPTS[0]))
        expected = _sequential_expected(module, params, cfg, PROMPTS[:1])
        assert good == expected[0]
    finally:
        batcher.close()


def test_moe_routed_decoder_streams_exactly():
    """Routed decoder through shared dispatches: free slots are done-masked so
    they claim no expert capacity, and each stream matches its solo run."""
    from unionml_tpu.models import MoEConfig, MoETransformer

    config = MoEConfig.tiny(
        vocab_size=61, dim=64, n_layers=2, n_heads=4, n_kv_heads=2, hidden_dim=96,
        n_experts=4, k=2, capacity_factor=8.0, dtype=jnp.float32, param_dtype=jnp.float32,
    )
    module = MoETransformer(config)
    params = module.init(jax.random.PRNGKey(2), jnp.zeros((1, 8), jnp.int32))["params"]
    cfg = GenerationConfig(max_new_tokens=6, temperature=0.0, prompt_buckets=(8,))
    prompts = [[3, 1, 4, 1, 5], [9, 2]]
    expected = _sequential_expected(module, params, cfg, prompts)

    batcher = ContinuousBatcher(Generator(module, params, cfg), slots=4, decode_chunk=2)
    try:
        streams = [batcher.submit(p) for p in prompts]
        assert [_drain(s) for s in streams] == expected
    finally:
        batcher.close()


def test_immediate_eos_masks_slot_and_streams_stay_exact(tiny_gen):
    """A prompt whose prompt-sampled first token is eos finishes at admission;
    its slot must be done-masked on device (the decode body never flags
    already-emitted tokens), or it would keep decoding as a zombie row."""
    module, params = tiny_gen
    probe = Generator(
        module, params, GenerationConfig(max_new_tokens=4, temperature=0.0, prompt_buckets=(16,))
    )(PROMPTS[:1])
    eos = int(probe[0][0])  # the very first sampled token for PROMPTS[0]
    cfg = GenerationConfig(
        max_new_tokens=8, temperature=0.0, prompt_buckets=(16,), eos_id=eos, pad_id=0
    )
    expected = _sequential_expected(module, params, cfg, PROMPTS[:3])

    batcher = ContinuousBatcher(Generator(module, params, cfg), slots=4, decode_chunk=3)
    try:
        streams = [batcher.submit(p) for p in PROMPTS[:3]]
        results = [_drain(s) for s in streams]
        assert results == expected
        assert results[0] == [eos]  # finished at admission
        # every slot is masked out once idle — no zombie rows left decoding
        done = np.asarray(batcher._carry[3])
        assert bool(done.all())
    finally:
        batcher.close()


def test_close_drains_residents_and_rejects_new(tiny_gen):
    module, params = tiny_gen
    cfg = GenerationConfig(max_new_tokens=24, temperature=0.0, prompt_buckets=(16,))
    expected = _sequential_expected(module, params, cfg, PROMPTS[:2])
    batcher = ContinuousBatcher(Generator(module, params, cfg), slots=2, decode_chunk=2)
    streams = [batcher.submit(p) for p in PROMPTS[:2]]
    # let the engine admit them before closing
    first = [next(iter_) for iter_ in streams]
    batcher.close(wait=False)
    with pytest.raises(RuntimeError, match="closed"):
        batcher.submit(PROMPTS[2])
    results = [
        [int(t) for t in np.asarray(f).ravel()] + _drain(s) for f, s in zip(first, streams)
    ]
    assert results == expected  # residents drained to completion, not truncated
    batcher.close()  # idempotent


def test_per_request_budget_and_int8_kv(tiny_gen):
    """Composition: per-request max_new_tokens caps below the config budget
    (the truncated stream is a prefix of the full one), and the int8 KV cache
    flows through admission/decode (quantized rows paste + stream)."""
    module, params = tiny_gen
    cfg = GenerationConfig(
        max_new_tokens=10, temperature=0.0, prompt_buckets=(16,), kv_cache_dtype="int8"
    )
    expected = _sequential_expected(module, params, cfg, PROMPTS[:2])

    batcher = ContinuousBatcher(Generator(module, params, cfg), slots=2, decode_chunk=3)
    try:
        full = _drain(batcher.submit(PROMPTS[0]))
        assert full == expected[0]
        short = _drain(batcher.submit(PROMPTS[1], max_new_tokens=4))
        assert short == expected[1][:4]
        with pytest.raises(ValueError, match="max_new_tokens"):
            batcher.submit(PROMPTS[0], max_new_tokens=11)
        with pytest.raises(ValueError, match="max_new_tokens"):
            batcher.submit(PROMPTS[0], max_new_tokens=0)
    finally:
        batcher.close()


def test_shared_prefix_across_slots(tiny_gen):
    """A server-wide prefix (system prompt) composes with continuous batching:
    every admitted suffix decodes as if prefilled with (prefix + suffix), and
    the prefix's prefill was paid once in cache_prefix."""
    module, params = tiny_gen
    cfg = GenerationConfig(max_new_tokens=8, temperature=0.0, prompt_buckets=(8, 32))
    prefix = [7, 7, 3, 9, 1, 2]
    suffixes = [[3, 1, 4], [9, 2, 6, 5], [8]]
    expected = _sequential_expected(module, params, cfg, [prefix + s for s in suffixes])

    gen = Generator(module, params, cfg)
    batcher = ContinuousBatcher(gen, slots=2, decode_chunk=3, prefix=gen.cache_prefix(prefix))
    try:
        results = [_drain(batcher.submit(s)) for s in suffixes]
        assert results == expected
    finally:
        batcher.close()


def test_prefix_with_oversized_prefill_chunk(tiny_gen):
    """cache_len must cover the chunk-ALIGNED prefill width: with prefill_chunk
    larger than bucket + budget + decode_chunk, the offset chunked prefill
    writes [p0, p0 + aligned) — round-3 sizing stopped at the budget tail, so
    dynamic_update_slice clamping silently corrupted earlier cache positions
    (ADVICE r3). The oracle would catch the corruption; the sizing assert pins
    the fix directly."""
    module, params = tiny_gen
    cfg = GenerationConfig(
        max_new_tokens=6, temperature=0.0, prompt_buckets=(8,), prefill_chunk=32
    )
    prefix = [7, 7]
    suffixes = [[3, 1, 4], [9, 2, 6, 5, 8, 1]]
    expected = _sequential_expected(module, params, cfg, [prefix + s for s in suffixes])

    gen = Generator(module, params, cfg)
    batcher = ContinuousBatcher(gen, slots=2, decode_chunk=3, prefix=gen.cache_prefix(prefix))
    try:
        assert batcher.cache_len >= len(prefix) + 32  # the aligned write fits
        results = [_drain(batcher.submit(s)) for s in suffixes]
        assert results == expected
    finally:
        batcher.close()


def _draft_for(vocab):
    cfg = LlamaConfig.tiny(
        vocab_size=vocab, dim=32, n_layers=1, n_heads=4, n_kv_heads=2, hidden_dim=64,
        dtype=jnp.float32, param_dtype=jnp.float32,
    )
    module = Llama(cfg)
    return module, module.init(jax.random.PRNGKey(9), jnp.zeros((1, 8), jnp.int32))["params"]


def test_speculative_continuous_streams_match_sequential(tiny_gen):
    """Speculative continuous batching: resident rows advance by shared
    draft-and-verify rounds with per-row floors, yet each greedy stream equals
    the plain sequential Generator run — the exactness oracle survives both
    compositions at once."""
    import dataclasses

    from unionml_tpu.models import DraftSpec

    module, params = tiny_gen
    base = GenerationConfig(max_new_tokens=10, temperature=0.0, prompt_buckets=(16,))
    expected = _sequential_expected(module, params, base, PROMPTS)

    draft, dp = _draft_for(97)
    cfg = dataclasses.replace(base, draft=DraftSpec(module=draft, params=dp, gamma=3))
    batcher = ContinuousBatcher(Generator(module, params, cfg), slots=3, decode_chunk=4)
    try:
        results = [None] * len(PROMPTS)

        def worker(i):
            results[i] = _drain(batcher.submit(PROMPTS[i]))

        threads = [threading.Thread(target=worker, args=(i,)) for i in range(len(PROMPTS))]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=300)
        assert results == expected
        assert batcher.decoded_rows > batcher.decode_dispatches  # rounds were shared
    finally:
        batcher.close()


def test_speculative_continuous_eos_and_budget(tiny_gen):
    import dataclasses

    from unionml_tpu.models import DraftSpec

    module, params = tiny_gen
    probe = Generator(
        module, params, GenerationConfig(max_new_tokens=12, temperature=0.0, prompt_buckets=(16,))
    )(PROMPTS[:1])
    eos = int(probe[0][4])
    base = GenerationConfig(
        max_new_tokens=12, temperature=0.0, prompt_buckets=(16,), eos_id=eos, pad_id=0
    )
    expected = _sequential_expected(module, params, base, PROMPTS[:3])

    draft, dp = _draft_for(97)
    cfg = dataclasses.replace(base, draft=DraftSpec(module=draft, params=dp, gamma=4))
    batcher = ContinuousBatcher(Generator(module, params, cfg), slots=1, decode_chunk=5)
    try:
        # slots=1 forces strict slot reuse; eos exits must free it
        results = [_drain(batcher.submit(p)) for p in PROMPTS[:3]]
        assert results == expected
        # per-request budget caps below eos
        short = _drain(batcher.submit(PROMPTS[1], max_new_tokens=2))
        assert short == expected[1][:2]
    finally:
        batcher.close()


def test_paged_kv_matches_sequential_with_undersized_pool(tiny_gen):
    """Paged KV capacity win: requests with small budgets are allocated only the
    blocks they need, so a pool FAR smaller than slots x worst-case admits a
    full house concurrently — and every stream is still token-exact against the
    sequential dense run (paged == contiguous == sequential)."""
    module, params = tiny_gen
    cfg = GenerationConfig(max_new_tokens=12, temperature=0.0, prompt_buckets=(16,))
    expected = _sequential_expected(module, params, cfg, PROMPTS[:4])

    batcher = ContinuousBatcher(
        Generator(module, params, cfg), slots=4, decode_chunk=4, block_size=8, pool_blocks=10
    )
    try:
        # worst-case sizing would need slots * max_blocks; the pool is smaller
        assert batcher.pool_blocks < batcher.slots * batcher.max_blocks
        # every request (budget 4) needs few enough blocks that all 4 fit at once
        assert 4 * batcher._blocks_lifetime(PROMPTS[0], 4) <= batcher.pool_blocks
        results = [None] * 4

        def worker(i):
            results[i] = _drain(batcher.submit(PROMPTS[i], max_new_tokens=4))

        threads = [threading.Thread(target=worker, args=(i,)) for i in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=180)
        assert results == [e[:4] for e in expected]
        assert batcher.decoded_rows > batcher.decode_dispatches  # dispatches were shared
        stats = batcher.stats()["kv_blocks"]
        # the byte gauges (block_bytes/used_bytes/kv_dtype) ride along at the
        # pool dtype; the allocator counters are the contract here
        assert {k: stats[k] for k in ("total", "used", "shared_prefix", "block_size", "preemptions")} == {
            "total": 10, "used": 0, "shared_prefix": 0, "block_size": 8, "preemptions": 0,
        }  # all freed, nobody evicted
        assert stats["used_bytes"] == 0 and stats["block_bytes"] > 0
    finally:
        batcher.close()


def test_paged_kv_pressure_waits_and_stays_exact(tiny_gen):
    """Pool pressure: with room for only ~2 resident requests, the third waits
    at the FIFO head until blocks free up — every stream still exact, and the
    allocator ends balanced."""
    module, params = tiny_gen
    cfg = GenerationConfig(max_new_tokens=10, temperature=0.0, prompt_buckets=(16,))
    expected = _sequential_expected(module, params, cfg, PROMPTS)

    gen = Generator(module, params, cfg)
    probe = ContinuousBatcher(gen, slots=3, decode_chunk=3, block_size=8)
    min_pool = probe.max_blocks  # the smallest legal pool: one worst-case request
    probe.close()
    batcher = ContinuousBatcher(gen, slots=3, decode_chunk=3, block_size=8, pool_blocks=min_pool)
    try:
        results = [None] * len(PROMPTS)

        def worker(i):
            results[i] = _drain(batcher.submit(PROMPTS[i]))

        threads = [threading.Thread(target=worker, args=(i,)) for i in range(len(PROMPTS))]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=300)
        assert results == expected
        assert batcher.stats()["kv_blocks"]["used"] == 0
    finally:
        batcher.close()


def test_paged_kv_with_prefix_and_int8(tiny_gen):
    """Paged KV composes with the shared prefix (prefix rows scatter into each
    admission's blocks) and the int8 KV cache (quantized pools + scale pools)."""
    module, params = tiny_gen
    cfg = GenerationConfig(
        max_new_tokens=8, temperature=0.0, prompt_buckets=(8, 16), kv_cache_dtype="int8"
    )
    prefix = [7, 7, 3, 9, 1, 2]
    suffixes = [[3, 1, 4], [9, 2, 6, 5], [8]]
    expected = _sequential_expected(module, params, cfg, [prefix + s for s in suffixes])

    gen = Generator(module, params, cfg)
    batcher = ContinuousBatcher(
        gen, slots=2, decode_chunk=3, prefix=gen.cache_prefix(prefix), block_size=8
    )
    try:
        results = [_drain(batcher.submit(s)) for s in suffixes]
        assert results == expected
    finally:
        batcher.close()


def test_paged_kv_oversized_prompt_fails_cleanly(tiny_gen):
    """A prompt whose block need exceeds a table row fails ITS stream without
    wedging the FIFO; later requests proceed."""
    module, params = tiny_gen
    cfg = GenerationConfig(max_new_tokens=6, temperature=0.0, prompt_buckets=(16,))
    expected = _sequential_expected(module, params, cfg, PROMPTS[:1])

    batcher = ContinuousBatcher(Generator(module, params, cfg), slots=2, decode_chunk=3, block_size=8)
    try:
        doomed = batcher.submit(list(range(1, 40)))  # buckets to 64 > cache_len
        ok = batcher.submit(PROMPTS[0])
        with pytest.raises(ValueError, match="blocks"):
            _drain(doomed)
        assert _drain(ok) == expected[0]
    finally:
        batcher.close()


def test_paged_preemption_recovers_token_exact(tiny_gen):
    """Pool exhaustion mid-decode preempts the YOUNGEST resident (freed,
    requeued as prompt + emitted tokens, re-prefilled) — and the evicted
    stream's total output is still exactly its sequential run: recompute
    preemption is invisible in tokens. Pool = one worst-case request, so two
    long-budget residents cannot coexist to completion."""
    module, params = tiny_gen
    cfg = GenerationConfig(max_new_tokens=16, temperature=0.0, prompt_buckets=(16,))
    expected = _sequential_expected(module, params, cfg, PROMPTS[:3])

    gen = Generator(module, params, cfg)
    probe = ContinuousBatcher(gen, slots=3, decode_chunk=2, block_size=8)
    min_pool = probe.max_blocks
    probe.close()
    batcher = ContinuousBatcher(gen, slots=3, decode_chunk=2, block_size=8, pool_blocks=min_pool)
    try:
        results = [None] * 3

        def worker(i):
            results[i] = _drain(batcher.submit(PROMPTS[i]))

        threads = [threading.Thread(target=worker, args=(i,)) for i in range(3)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=300)
        assert results == expected
        stats = batcher.stats()["kv_blocks"]
        assert stats["preemptions"] > 0  # the tight pool actually evicted someone
        assert stats["used"] == 0
    finally:
        batcher.close()


def test_paged_preempted_resume_outgrows_buckets(tiny_gen):
    """A preempted stream's resume prompt (original + emitted) can exceed every
    configured prompt bucket; the resume must prefill at exact width and stay
    token-exact instead of failing the stream mid-generation (round-4 review
    repro: bucket 16, resume length 19 -> oversized-bucket ValueError)."""
    module, params = tiny_gen
    cfg = GenerationConfig(max_new_tokens=16, temperature=0.0, prompt_buckets=(16,))
    long_prompts = [[3, 1, 4, 1, 5, 9, 2, 6, 5, 3, 5, 8, 9, 7], [2, 7, 1, 8, 2, 8, 1, 8, 2, 8, 4, 5, 9, 0o4]]
    expected = _sequential_expected(module, params, cfg, long_prompts)

    gen = Generator(module, params, cfg)
    probe = ContinuousBatcher(gen, slots=2, decode_chunk=8, block_size=8)
    # big enough to ADMIT both (initial needs), too small for both to finish —
    # and chunk 8 means the victim has a full chunk in its echo at eviction,
    # so its resume prompt (14 + 9 = 23) overflows the single 16-wide bucket
    pool = 2 * probe._blocks_initial(long_prompts[0], cfg.max_new_tokens)
    assert pool < 2 * probe._blocks_lifetime(long_prompts[0], cfg.max_new_tokens)
    probe.close()
    batcher = ContinuousBatcher(gen, slots=2, decode_chunk=8, block_size=8, pool_blocks=pool)
    try:
        results = [None] * 2

        def worker(i):
            results[i] = _drain(batcher.submit(long_prompts[i]))

        threads = [threading.Thread(target=worker, args=(i,)) for i in range(2)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=300)
        assert results == expected
        assert batcher.stats()["kv_blocks"]["preemptions"] > 0  # the repro actually fired
    finally:
        batcher.close()


def test_paged_lazy_growth_admits_beyond_reserved_budgets(tiny_gen):
    """Lazy allocation: admission reserves only prompt + one dispatch, so a
    pool far below the residents' SUMMED lifetime needs still admits them all
    concurrently — blocks arrive as decoding actually proceeds."""
    module, params = tiny_gen
    cfg = GenerationConfig(max_new_tokens=12, temperature=0.0, prompt_buckets=(16,))
    expected = _sequential_expected(module, params, cfg, PROMPTS[:4])

    gen = Generator(module, params, cfg)
    batcher = ContinuousBatcher(gen, slots=4, decode_chunk=3, block_size=8, pool_blocks=8)
    try:
        # the pool cannot hold 4 lifetime reservations...
        assert 4 * batcher._blocks_lifetime(PROMPTS[0], cfg.max_new_tokens) > batcher.pool_blocks
        # ...but it CAN admit all 4 (initial needs only)
        assert 4 * batcher._blocks_initial(PROMPTS[0], cfg.max_new_tokens) <= batcher.pool_blocks
        results = [None] * 4

        def worker(i):
            results[i] = _drain(batcher.submit(PROMPTS[i]))

        threads = [threading.Thread(target=worker, args=(i,)) for i in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=300)
        assert results == expected
    finally:
        batcher.close()


def test_paged_shared_prefix_pages(tiny_gen):
    """A long system prompt's FULL blocks are seeded once and SHARED: every
    slot's table points at the same page ids (vLLM's prefix caching), so
    per-request allocation shrinks by the shared pages — and tokens still equal
    the sequential dense run."""
    module, params = tiny_gen
    cfg = GenerationConfig(max_new_tokens=6, temperature=0.0, prompt_buckets=(8, 32))
    prefix = [7, 7, 3, 9, 1, 2, 5, 11, 4, 8, 2, 6, 9, 1, 3, 2, 8, 4, 1, 5]  # 20 tokens
    suffixes = [[3, 1, 4], [9, 2, 6, 5], [8]]
    expected = _sequential_expected(module, params, cfg, [prefix + s for s in suffixes])

    gen = Generator(module, params, cfg)
    batcher = ContinuousBatcher(
        gen, slots=2, decode_chunk=3, prefix=gen.cache_prefix(prefix), block_size=8
    )
    try:
        assert len(batcher._shared_prefix_blocks) == 2  # 20 // 8
        # admission need excludes the shared pages: ceil((20+4+3+3)/8)=4 - 2
        assert batcher._blocks_initial(suffixes[1], 6) == 2
        results = [_drain(batcher.submit(s)) for s in suffixes]
        assert results == expected
        stats = batcher.stats()["kv_blocks"]
        assert stats["shared_prefix"] == 2
        assert stats["used"] == 2  # only the permanently resident shared pages
    finally:
        batcher.close()


def test_paged_speculative_with_prefix_all_compositions(tiny_gen):
    """Everything at once: paged KV x speculative x shared prefix x per-request
    budgets. One block allocation drives both models' pools; each greedy stream
    equals the sequential plain run on (prefix + suffix)."""
    import dataclasses

    from unionml_tpu.models import DraftSpec

    module, params = tiny_gen
    base = GenerationConfig(max_new_tokens=8, temperature=0.0, prompt_buckets=(8, 16))
    prefix = [7, 7, 3, 9]
    suffixes = [[3, 1, 4], [9, 2, 6, 5], [8]]
    expected = _sequential_expected(module, params, base, [prefix + s for s in suffixes])

    draft, dp = _draft_for(97)
    cfg = dataclasses.replace(base, draft=DraftSpec(module=draft, params=dp, gamma=3))
    gen = Generator(module, params, cfg)
    batcher = ContinuousBatcher(
        gen, slots=2, decode_chunk=3, prefix=gen.cache_prefix(prefix), block_size=8
    )
    try:
        results = [_drain(batcher.submit(s)) for s in suffixes]
        assert results == expected
        short = _drain(batcher.submit(suffixes[0], max_new_tokens=3))
        assert short == expected[0][:3]
        assert batcher.stats()["kv_blocks"]["used"] == 0  # allocator balanced
    finally:
        batcher.close()


def test_speculative_continuous_with_shared_prefix(tiny_gen):
    """The production trifecta — system prompt (prefix=) + draft model
    (speculative) + continuous batching — in one engine: every greedy stream
    equals the sequential plain-Generator run on (prefix + suffix)."""
    import dataclasses

    from unionml_tpu.models import DraftSpec

    module, params = tiny_gen
    base = GenerationConfig(max_new_tokens=8, temperature=0.0, prompt_buckets=(8, 16))
    prefix = [7, 7, 3, 9, 1, 2]
    suffixes = [[3, 1, 4], [9, 2, 6, 5], [8], [2, 2]]
    expected = _sequential_expected(module, params, base, [prefix + s for s in suffixes])

    draft, dp = _draft_for(97)
    cfg = dataclasses.replace(base, draft=DraftSpec(module=draft, params=dp, gamma=3))
    gen = Generator(module, params, cfg)
    batcher = ContinuousBatcher(gen, slots=2, decode_chunk=3, prefix=gen.cache_prefix(prefix))
    try:
        results = [None] * len(suffixes)

        def worker(i):
            results[i] = _drain(batcher.submit(suffixes[i]))

        threads = [threading.Thread(target=worker, args=(i,)) for i in range(len(suffixes))]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=300)
        assert results == expected
    finally:
        batcher.close()


def test_chunked_admission_streams_match_sequential(tiny_gen):
    """Stall-free admission: prefill sliced into admit_chunk-token chunks
    interleaved with decode must be invisible in the output — every stream
    equals its monolithic/sequential run (the chunked-prefill equality
    contract), and the chunk counters show the slicing actually happened."""
    module, params = tiny_gen
    cfg = GenerationConfig(max_new_tokens=12, temperature=0.0, prompt_buckets=(16,))
    expected = _sequential_expected(module, params, cfg, PROMPTS)

    batcher = ContinuousBatcher(
        Generator(module, params, cfg), slots=len(PROMPTS), decode_chunk=4, admit_chunk=4
    )
    try:
        results = [None] * len(PROMPTS)

        def worker(i):
            results[i] = _drain(batcher.submit(PROMPTS[i]))

        threads = [threading.Thread(target=worker, args=(i,)) for i in range(len(PROMPTS))]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=120)
        assert results == expected
        stats = batcher.stats()
        assert stats["prefill"]["mode"] == "chunked"
        assert stats["prefill"]["chunks"] >= len(PROMPTS)  # every admission chunked
        assert stats["prefill"]["monolithic_admissions"] == 0
        # TTFT/TBT reservoirs filled (the /metrics surface)
        assert stats["ttft_ms"]["window"] == len(PROMPTS)
        assert stats["tbt_ms"]["window"] > 0
    finally:
        batcher.close()


def test_chunked_admission_interleaves_decode_with_prefill(tiny_gen):
    """The stall fix itself: while a multi-chunk admission is in flight, the
    resident stream keeps receiving tokens — decode dispatches land BETWEEN
    prefill chunks (budget = one chunk per engine iteration), instead of the
    whole prompt prefilling in one stall."""
    module, params = tiny_gen
    cfg = GenerationConfig(max_new_tokens=48, temperature=0.0, prompt_buckets=(4, 16))
    gen = Generator(module, params, cfg)
    expected = _sequential_expected(module, params, cfg, [[5, 5, 5], [9] * 12])
    batcher = ContinuousBatcher(gen, slots=2, decode_chunk=2, admit_chunk=4, prefill_budget=4)
    try:
        occupant = batcher.submit([5, 5, 5])
        first = next(occupant)  # resident and decoding (48-token budget)
        dispatches_at_chunk = []
        orig = gen._prefill_chunk

        def spy(*args, **kwargs):
            dispatches_at_chunk.append(batcher.decode_dispatches)
            return orig(*args, **kwargs)

        gen._prefill_chunk = spy
        try:
            long_out = _drain(batcher.submit([9] * 12))  # bucket 16 -> 4 chunks
        finally:
            gen._prefill_chunk = orig
        occ_out = [int(t) for t in np.asarray(first).ravel()] + _drain(occupant)
        assert [occ_out, long_out] == expected
        assert len(dispatches_at_chunk) == 4  # 16 aligned columns / 4-token chunks
        # decode ran between every pair of chunks: the dispatch counter
        # strictly increases across the admission instead of freezing
        assert all(
            b > a for a, b in zip(dispatches_at_chunk, dispatches_at_chunk[1:])
        ), dispatches_at_chunk
    finally:
        batcher.close()


def test_prefill_budget_groups_chunks_per_iteration(tiny_gen):
    """prefill_budget tokens of prefill run per engine iteration: with a
    budget of two chunks, chunks land in pairs between decode dispatches."""
    module, params = tiny_gen
    cfg = GenerationConfig(max_new_tokens=48, temperature=0.0, prompt_buckets=(4, 32))
    gen = Generator(module, params, cfg)
    batcher = ContinuousBatcher(gen, slots=2, decode_chunk=2, admit_chunk=4, prefill_budget=8)
    try:
        occupant = batcher.submit([5, 5, 5])
        next(occupant)
        dispatches_at_chunk = []
        orig = gen._prefill_chunk

        def spy(*args, **kwargs):
            dispatches_at_chunk.append(batcher.decode_dispatches)
            return orig(*args, **kwargs)

        gen._prefill_chunk = spy
        try:
            _drain(batcher.submit([9] * 20, max_new_tokens=2))  # bucket 32 -> 8 chunks
        finally:
            gen._prefill_chunk = orig
        _drain(occupant)
        assert len(dispatches_at_chunk) == 8
        # chunks arrive in pairs: both members of a pair see the same decode
        # count, and decode advances between pairs
        pairs = list(zip(dispatches_at_chunk[0::2], dispatches_at_chunk[1::2]))
        assert all(a == b for a, b in pairs), dispatches_at_chunk
        assert all(n[0] > p[0] for p, n in zip(pairs, pairs[1:])), dispatches_at_chunk
    finally:
        batcher.close()


def test_cancel_mid_chunked_prefill_frees_slot(tiny_gen):
    """A consumer disconnect landing between prefill chunks abandons the
    admission at the next chunk boundary: the slot comes back (no device
    masking needed — the row was never pasted) and later requests are exact."""
    module, params = tiny_gen
    cfg = GenerationConfig(max_new_tokens=8, temperature=0.0, prompt_buckets=(16,))
    gen = Generator(module, params, cfg)
    expected = _sequential_expected(module, params, cfg, PROMPTS[:2])
    batcher = ContinuousBatcher(gen, slots=1, decode_chunk=2, admit_chunk=8)
    try:
        entered, gate = threading.Event(), threading.Event()
        orig = gen._prefill_chunk

        def gated(*args, **kwargs):
            entered.set()
            gate.wait(timeout=30)
            return orig(*args, **kwargs)

        gen._prefill_chunk = gated
        doomed = batcher.submit(PROMPTS[2])  # bucket 16 -> 2 chunks
        assert entered.wait(timeout=30)  # engine inside chunk 1 of 2
        doomed.close()  # cancel lands mid-prefill
        gate.set()
        gen._prefill_chunk = orig
        assert _drain(doomed) == []
        out = [_drain(batcher.submit(p)) for p in PROMPTS[:2]]
        assert out == expected
        stats = batcher.stats()
        assert stats["resident"] == 0 and stats["waiting"] == 0 and stats["admitting"] == 0
    finally:
        batcher.close()


def test_deadline_shed_mid_chunked_prefill(tiny_gen):
    """A deadline expiring between prefill chunks sheds the admission with
    DeadlineExceeded at the next chunk boundary — the client gave up, so the
    remaining chunks and the whole decode are never paid — and the freed slot
    serves the next request exactly."""
    import time as _time

    module, params = tiny_gen
    cfg = GenerationConfig(max_new_tokens=8, temperature=0.0, prompt_buckets=(16,))
    gen = Generator(module, params, cfg)
    expected = _sequential_expected(module, params, cfg, PROMPTS[:1])
    batcher = ContinuousBatcher(gen, slots=1, decode_chunk=2, admit_chunk=8)
    try:
        from unionml_tpu.serving import DeadlineExceeded

        entered, gate = threading.Event(), threading.Event()
        orig = gen._prefill_chunk

        def gated(*args, **kwargs):
            entered.set()
            gate.wait(timeout=30)
            return orig(*args, **kwargs)

        gen._prefill_chunk = gated
        doomed = batcher.submit(PROMPTS[2], deadline=_time.monotonic() + 0.2)
        assert entered.wait(timeout=30)  # admission started before the deadline
        _time.sleep(0.3)  # deadline passes while chunk 1 is in flight
        gate.set()
        gen._prefill_chunk = orig
        with pytest.raises(DeadlineExceeded, match="mid-prefill"):
            _drain(doomed)
        assert batcher.stats()["shed_deadline"] == 1
        assert _drain(batcher.submit(PROMPTS[0])) == expected[0]
    finally:
        batcher.close()


def test_chunked_admission_with_shared_prefix_and_speculative(tiny_gen):
    """Chunked admission composes with the production trifecta: the draft's
    row chunks in LOCKSTEP with the target's after both models' prefix rows
    paste, and every greedy stream equals the sequential plain run."""
    import dataclasses

    from unionml_tpu.models import DraftSpec

    module, params = tiny_gen
    base = GenerationConfig(max_new_tokens=8, temperature=0.0, prompt_buckets=(8, 16))
    prefix = [7, 7, 3, 9, 1, 2]
    suffixes = [[3, 1, 4], [9, 2, 6, 5], [8], [2, 2]]
    expected = _sequential_expected(module, params, base, [prefix + s for s in suffixes])

    draft, dp = _draft_for(97)
    cfg = dataclasses.replace(base, draft=DraftSpec(module=draft, params=dp, gamma=3))
    gen = Generator(module, params, cfg)
    batcher = ContinuousBatcher(
        gen, slots=2, decode_chunk=3, prefix=gen.cache_prefix(prefix), admit_chunk=4
    )
    try:
        results = [_drain(batcher.submit(s)) for s in suffixes]
        assert results == expected
        assert batcher.stats()["prefill"]["chunks"] > 0
    finally:
        batcher.close()


@pytest.mark.slow  # ~4s; the same preempt-resume-under-chunking path stays in
# tier-1 via tests/emulated/test_continuous_chunked.py's paged leg
def test_chunked_admission_paged_preemption_resume(tiny_gen):
    """Chunked admission preserves paged-KV pressure semantics: a preempted
    stream's resume (original + emitted tokens, outgrowing every bucket)
    still lands token-exact — the exact-width resume falls back to a
    monolithic prefill when its chunk-aligned width would overflow the
    cache, instead of failing the stream."""
    module, params = tiny_gen
    cfg = GenerationConfig(max_new_tokens=16, temperature=0.0, prompt_buckets=(16,))
    long_prompts = [[3, 1, 4, 1, 5, 9, 2, 6, 5, 3, 5, 8, 9, 7], [2, 7, 1, 8, 2, 8, 1, 8, 2, 8, 4, 5, 9, 4]]
    expected = _sequential_expected(module, params, cfg, long_prompts)

    gen = Generator(module, params, cfg)
    probe = ContinuousBatcher(gen, slots=2, decode_chunk=8, block_size=8, admit_chunk=8)
    pool = 2 * probe._blocks_initial(long_prompts[0], cfg.max_new_tokens)
    assert pool < 2 * probe._blocks_lifetime(long_prompts[0], cfg.max_new_tokens)
    probe.close()
    batcher = ContinuousBatcher(
        gen, slots=2, decode_chunk=8, block_size=8, pool_blocks=pool, admit_chunk=8
    )
    try:
        results = [None] * 2

        def worker(i):
            results[i] = _drain(batcher.submit(long_prompts[i]))

        threads = [threading.Thread(target=worker, args=(i,)) for i in range(2)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=300)
        assert results == expected
        stats = batcher.stats()
        assert stats["kv_blocks"]["preemptions"] > 0  # pressure actually fired
        assert stats["prefill"]["chunks"] > 0  # fresh admissions chunked
    finally:
        batcher.close()


def test_metrics_surface_ttft_tbt_and_prefill_counters(tiny_gen, sklearn_model):
    """/metrics regression for the stall-fix surface: the generation section
    carries ttft_ms/tbt_ms percentile blocks and the prefill counter block,
    and NO gauge anywhere in the snapshot is None-valued (an empty reservoir
    reports {"window": 0}, a missing engine omits its gauge entirely)."""
    import asyncio
    import json

    from unionml_tpu.serving import serving_app

    module, params = tiny_gen
    cfg = GenerationConfig(max_new_tokens=6, temperature=0.0, prompt_buckets=(16,))
    batcher = ContinuousBatcher(Generator(module, params, cfg), slots=2, decode_chunk=2, admit_chunk=4)
    try:
        _drain(batcher.submit(PROMPTS[0]))  # populate the reservoirs
        sklearn_model.train(hyperparameters={"max_iter": 200})
        sklearn_model.generation_batcher = batcher
        app = serving_app(sklearn_model)

        async def scenario():
            status, payload, _ = await app.dispatch("GET", "/metrics", b"")
            assert status == 200
            return json.loads(payload) if isinstance(payload, (bytes, str)) else payload

        payload = asyncio.run(scenario())
        generation = payload["generation"]
        assert {"ttft_ms", "tbt_ms", "prefill", "admitting"} <= set(generation)
        assert generation["ttft_ms"]["window"] >= 1
        assert {"chunks", "chunk_tokens", "monolithic_admissions", "backlog_tokens"} <= set(
            generation["prefill"]
        )

        def no_nones(node, path="snapshot"):
            if isinstance(node, dict):
                for k, v in node.items():
                    assert v is not None, f"None-valued gauge at {path}.{k}"
                    no_nones(v, f"{path}.{k}")
            elif isinstance(node, list):
                for i, v in enumerate(node):
                    no_nones(v, f"{path}[{i}]")

        no_nones(payload.get("gauges", {}), "gauges")
        no_nones(generation["ttft_ms"], "ttft_ms")
        no_nones(generation["tbt_ms"], "tbt_ms")
        no_nones(generation["prefill"], "prefill")
    finally:
        sklearn_model.generation_batcher = None
        batcher.close()


def test_cancelled_stream_frees_slot_for_waiters(tiny_gen):
    """Closing a stream's iterator (the client-disconnect path) releases its
    slot at the next chunk boundary; a queued request takes it and the
    remaining streams are unaffected."""
    module, params = tiny_gen
    cfg = GenerationConfig(max_new_tokens=24, temperature=0.0, prompt_buckets=(16,))
    expected = _sequential_expected(module, params, cfg, PROMPTS[:3])

    batcher = ContinuousBatcher(Generator(module, params, cfg), slots=1, decode_chunk=2)
    try:
        doomed = batcher.submit(PROMPTS[0])
        next(doomed)  # ensure it is admitted and producing
        doomed.close()  # consumer walks away mid-generation
        # the slot must come back: these would hang forever if it leaked
        out1 = _drain(batcher.submit(PROMPTS[1]))
        out2 = _drain(batcher.submit(PROMPTS[2]))
        assert [out1, out2] == expected[1:3]
        # the cancelled session is gone from the books
        stats = batcher.stats()
        assert stats["resident"] == 0 and stats["waiting"] == 0
    finally:
        batcher.close()


def test_cancel_while_pending_dequeues(tiny_gen):
    """close() on a stream abandoned BEFORE admission (never nexted — the
    generator-close blind spot _TokenStream exists for) dequeues it: it is
    never admitted, never decodes to a dead queue, and drains as an empty
    stream."""
    module, params = tiny_gen
    cfg = GenerationConfig(max_new_tokens=16, temperature=0.0, prompt_buckets=(16,))
    expected = _sequential_expected(module, params, cfg, PROMPTS[:2])
    batcher = ContinuousBatcher(Generator(module, params, cfg), slots=1, decode_chunk=2)
    try:
        first = batcher.submit(PROMPTS[0])
        next(first)  # occupies the single slot
        queued = batcher.submit(PROMPTS[1])  # waits for the slot
        assert batcher.stats()["waiting"] == 1
        queued.close()  # abandoned before admission, without a single next()
        assert batcher.stats()["waiting"] == 0  # dequeued immediately
        assert _drain(queued) == []  # ends cleanly, no tokens
        rest = _drain(first)
        # the abandoned request was never admitted: after `first` finishes the
        # engine goes idle instead of decoding the ghost
        assert batcher.stats()["resident"] == 0
        import time as _time

        idle_dispatches = batcher.decode_dispatches
        _time.sleep(1.0)
        assert batcher.decode_dispatches == idle_dispatches  # no ghost decoding
        again = _drain(batcher.submit(PROMPTS[1]))
        assert again == expected[1]
    finally:
        batcher.close()


def test_cancel_during_prefill_window_returns_slot(tiny_gen):
    """A cancel landing while the engine is inside the UNLOCKED prefill (the
    session is neither pending nor resident) must not register the dead
    session: the freshly activated row is masked back out and the slot is
    immediately reusable."""
    import time as _time

    module, params = tiny_gen
    cfg = GenerationConfig(max_new_tokens=16, temperature=0.0, prompt_buckets=(16,))
    expected = _sequential_expected(module, params, cfg, PROMPTS[:2])
    batcher = ContinuousBatcher(Generator(module, params, cfg), slots=1, decode_chunk=2)
    try:
        entered, gate = threading.Event(), threading.Event()
        orig = batcher._prefill_row

        def slow_prefill(prompt, seed, *args, **kwargs):
            entered.set()
            gate.wait(timeout=30)
            return orig(prompt, seed, *args, **kwargs)

        batcher._prefill_row = slow_prefill
        stream = batcher.submit(PROMPTS[0])
        assert entered.wait(timeout=30)  # engine is inside the prefill window
        stream.close()  # cancel lands while neither pending nor resident
        gate.set()
        assert _drain(stream) == []
        batcher._prefill_row = orig

        # the slot came back and serves a fresh request exactly
        out = _drain(batcher.submit(PROMPTS[1]))
        assert out == expected[1]
        stats = batcher.stats()
        assert stats["resident"] == 0 and stats["waiting"] == 0
    finally:
        batcher.close()


def test_warmup_compiles_every_bucket_then_serves_exactly(tiny_gen):
    """warmup() drives a bucket-FILLING request through each prompt bucket plus
    one decode chunk and resets the counters; real traffic afterwards is exact,
    starts from clean metrics, and — the point — triggers NO new prefill or
    decode traces in any bucket."""
    module, params = tiny_gen
    cfg = GenerationConfig(max_new_tokens=8, temperature=0.0, prompt_buckets=(8, 16))
    prompts = [PROMPTS[0], [5] * 12]  # land in bucket 8 and bucket 16
    expected = _sequential_expected(module, params, cfg, prompts)
    gen = Generator(module, params, cfg)
    batcher = ContinuousBatcher(gen, slots=2, decode_chunk=3)
    try:
        batcher.warmup()
        stats = batcher.stats()
        assert stats["decode_dispatches"] == 0 and stats["resident"] == 0
        prefill_traces = gen.prefill_traces
        decode_traces = gen.decode_traces
        results = [_drain(batcher.submit(p)) for p in prompts]
        assert results == expected
        assert batcher.decode_dispatches > 0
        assert gen.prefill_traces == prefill_traces  # both buckets pre-compiled
        assert gen.decode_traces == decode_traces  # decode chunk pre-compiled
    finally:
        batcher.close()


def test_overload_admission_deadline_and_disconnect(tiny_gen, sklearn_model):
    """Engine-level overload protection, one batcher for all three properties
    (a fresh Generator per property would triple the XLA compile bill):

    1. ``max_waiting`` bounds the slot-wait queue — the excess submission sheds
       synchronously with QueueFullError (the HTTP layer's 429).
    2. A waiter whose deadline passes while queued is shed with
       DeadlineExceeded at the next chunk boundary, never paying a prefill.
    3. A streaming client that disconnects mid-decode (the /predict-stream
       route's aclose path) frees its slot within one decode chunk — pinned
       against ``stats()['resident']`` — and the slot admits new work.
    """
    import asyncio
    import json
    import time

    from unionml_tpu.serving import DeadlineExceeded, QueueFullError, serving_app
    from unionml_tpu.serving.overload import QueueFullError as QFE

    assert QFE is QueueFullError  # one exception type across layers

    module, params = tiny_gen
    cfg = GenerationConfig(max_new_tokens=256, temperature=0.0, prompt_buckets=(16,))
    batcher = ContinuousBatcher(
        Generator(module, params, cfg), slots=1, decode_chunk=2, max_waiting=2
    )
    try:
        # ---- 1+2: bound the waiting queue and shed the expired waiter
        occupant = batcher.submit(PROMPTS[0])  # 256-token budget: owns the slot
        next(occupant)  # first token: resident now
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline and batcher.stats()["waiting"]:
            time.sleep(0.01)
        doomed = batcher.submit(PROMPTS[1], deadline=time.monotonic() + 0.02)
        waiter = batcher.submit(PROMPTS[3], max_new_tokens=4)
        with pytest.raises(QueueFullError, match="waiting queue full"):
            batcher.submit(PROMPTS[4])  # 3rd waiter > max_waiting=2
        assert batcher.stats()["shed_queue_full"] == 1
        time.sleep(0.05)  # doomed's deadline passes while it waits
        with pytest.raises(DeadlineExceeded):
            _drain(doomed)
        assert batcher.stats()["shed_deadline"] == 1
        _drain(occupant)  # release the slot; waiter decodes next
        assert len(_drain(waiter)) == 4

        # ---- 3: route-level disconnect frees the slot within one chunk
        sklearn_model.train(hyperparameters={"max_iter": 200})

        @sklearn_model.stream_predictor
        def stream_predictor(model_object, features):
            for chunk in batcher.submit([3, 1, 4, 1, 5]):
                yield chunk.tolist()

        sklearn_model.generation_batcher = batcher
        app = serving_app(sklearn_model)

        async def scenario():
            status, payload, _ = await app.dispatch(
                "POST", "/predict-stream", json.dumps({"features": [{"x": 1.0}]}).encode()
            )
            assert status == 200
            agen = payload.__aiter__()
            await agen.__anext__()  # decode underway (256-token budget ~= forever)
            assert batcher.stats()["resident"] == 1
            await agen.aclose()  # in-process client disconnect
            # the engine must free the slot at the next chunk boundary; poll on
            # THIS loop so the route's detached iterator-close task can run
            for _ in range(400):
                if batcher.stats()["resident"] == 0:
                    break
                await asyncio.sleep(0.025)
            assert batcher.stats()["resident"] == 0, "slot leaked after disconnect"

        asyncio.run(scenario())
        # the freed slot admits new work and decodes it to completion
        out = _drain(batcher.submit(PROMPTS[5], max_new_tokens=4))
        assert len(out) == 4
    finally:
        batcher.close()
