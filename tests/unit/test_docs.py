"""Docs pipeline tests: the site builds, the tutorial executes, the notebook
conversion is deterministic (reference analog: scripts/myst_to_ipynb.py + the
Sphinx site under docs/source)."""

import json
import re
import sys
from pathlib import Path

import pytest

DOCS = Path(__file__).resolve().parents[2] / "docs"
sys.path.insert(0, str(DOCS))

from build import build_site, render_markdown  # noqa: E402
from md_to_ipynb import convert  # noqa: E402

TUTORIAL = DOCS / "tutorials" / "quickstart_tutorial.md"
GENERATION_TUTORIAL = DOCS / "tutorials" / "generation_tutorial.md"


def test_site_builds_all_pages(tmp_path):
    written = build_site(tmp_path)
    names = {p.name for p in written}
    for expected in (
        "index.html",
        "quickstart.html",
        "tpu-training.html",
        "parallelism.html",
        "generation.html",
        "serving.html",
        "remote.html",
        "benchmarks.html",
        "quickstart_tutorial.html",
    ):
        assert expected in names
    index = (tmp_path / "index.html").read_text()
    assert "<nav>" in index and "unionml-tpu" in index
    # .md cross-links are rewritten to .html
    assert 'href="quickstart.html"' in index and ".md\"" not in index


def test_markdown_rendering_features():
    html = render_markdown(
        "# Title\n\nSome `code` and **bold** text with a [link](other.md).\n\n"
        "```python\nx = 1 < 2\n```\n\n- item one\n- item two\n\n"
        "| a | b |\n|---|---|\n| 1 | 2 |\n"
    )
    assert "<h1>Title</h1>" in html
    assert "<code>code</code>" in html and "<strong>bold</strong>" in html
    assert 'href="other.html"' in html
    assert "x = 1 &lt; 2" in html  # code is escaped
    assert "<li>item one</li>" in html
    assert "<th>a</th>" in html and "<td>2</td>" in html


@pytest.mark.slow
def test_tutorial_code_blocks_execute_end_to_end():
    """The quickstart tutorial's python blocks run top-to-bottom — the doc is an
    executable artifact, not prose that can rot. Marked slow: it trains a real
    model (~10s), which doesn't belong in the tier-1 time budget."""
    source = TUTORIAL.read_text()
    blocks = re.findall(r"```python\n(.*?)\n```", source, flags=re.DOTALL)
    assert len(blocks) >= 4
    namespace: dict = {}
    exec(compile("\n\n".join(blocks), str(TUTORIAL), "exec"), namespace)  # noqa: S102
    assert namespace["metrics"]["train"] > 0.9


@pytest.mark.slow
def test_generation_tutorial_executes_end_to_end():
    source = GENERATION_TUTORIAL.read_text()
    blocks = re.findall(r"```python\n(.*?)\n```", source, flags=re.DOTALL)
    assert len(blocks) >= 5
    namespace: dict = {}
    exec(compile("\n\n".join(blocks), str(GENERATION_TUTORIAL), "exec"), namespace)  # noqa: S102
    assert namespace["tokens"].shape == (2, 16)


def test_notebook_conversion_is_deterministic():
    first = convert(TUTORIAL)
    second = convert(TUTORIAL)
    assert json.dumps(first) == json.dumps(second)
    kinds = [c["cell_type"] for c in first["cells"]]
    assert "code" in kinds and "markdown" in kinds
    ids = [c["id"] for c in first["cells"]]
    assert len(ids) == len(set(ids))  # unique, deterministic ids
    code = "".join("".join(c["source"]) for c in first["cells"] if c["cell_type"] == "code")
    assert "model.train" in code
