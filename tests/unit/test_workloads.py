"""Traffic record/replay engine (docs/workloads.md) + per-tenant SLO verdicts.

The pinned contracts:

- **schema**: the versioned ndjson trace round-trips exactly, foreign/newer
  headers are rejected with a clear error, and serialization is canonical —
  the determinism story is byte-level;
- **scenarios**: ``synthesize(name, seed)`` is a pure function — same seed,
  byte-identical trace text; different seeds differ;
- **capture**: the ``--record-traffic`` tap records parsed ``/v1`` and
  ``/predict-stream`` requests (tenant/priority included) into a replayable
  trace; hashed mode keeps lengths + digests, never token ids;
- **replayer**: open-loop playback through the real HTTP dispatch surface
  collects per-tenant TTFT/TBT/shed aggregates and reports wall-clock
  schedule adherence honestly (a harness that fell behind says so);
- **verdicts**: observed-vs-target burn rates classify pass/warn/breach,
  min-samples gated, None-free;
- **per-tenant SLOs**: the engine keys bounded-LRU SLO state per tenant with
  armed ``TenantSpec.slo_*`` targets, the sections ride ``stats()`` →
  ``/metrics`` (Prometheus render None-free) and ``/healthz``, and
  target-less/tenancy-off engines stay byte-for-byte unchanged;
- **OpenAI stop=/logprobs**: no longer 400 — stop truncates at the earliest
  match with ``finish_reason: "stop"``, logprobs surfaces the sampled
  token's log-probability in chunks and final choices.
"""

import asyncio
import json
import types

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from unionml_tpu.models import GenerationConfig, Generator, Llama, LlamaConfig
from unionml_tpu.observability.slo import SLOConfig, TenantSLORegistry
from unionml_tpu.serving import (
    ContinuousBatcher,
    ReplicaScheduler,
    ServingApp,
    TenantRegistry,
    TenantSpec,
)
from unionml_tpu.workloads import (
    SCENARIOS,
    TraceRecorder,
    TraceRequest,
    dumps_trace,
    read_trace,
    replay,
    scenario_targets,
    set_active_traffic_recorder,
    synthesize,
    synthesize_text,
    tenant_verdicts,
    write_trace,
)
from unionml_tpu.workloads.replayer import _Record, _report
from unionml_tpu.workloads.traces import loads_trace
from unionml_tpu.workloads.verdicts import overall_state


@pytest.fixture(scope="module")
def tiny():
    config = LlamaConfig.tiny(
        vocab_size=96, dim=64, n_layers=2, n_heads=4, n_kv_heads=2, hidden_dim=128,
        dtype=jnp.float32, param_dtype=jnp.float32,
    )
    module = Llama(config)
    params = module.init(jax.random.PRNGKey(1), jnp.zeros((1, 8), jnp.int32))["params"]
    return module, params


def _cfg(**overrides):
    kwargs = dict(max_new_tokens=8, temperature=0.0, prompt_buckets=(16,))
    kwargs.update(overrides)
    return GenerationConfig(**kwargs)


def _app(tiny, cfg=None, tenancy=None, **engine_kwargs):
    module, params = tiny
    engine = ContinuousBatcher(
        Generator(module, params, cfg or _cfg()), slots=2, tenancy=tenancy, **engine_kwargs
    )
    model = types.SimpleNamespace(
        artifact=object(), generation_batcher=engine, _predictor_config=None,
        _compiled_predictor=None, _stream_predictor=None, name="tiny",
    )
    app = ServingApp(model)
    app._started = True
    return app, engine


def _dispatch(app, method, path, body=b"", headers=None):
    return asyncio.run(app.server.dispatch_with_headers(method, path, body, headers))


def _dispatch_stream(app, method, path, body=b"", headers=None):
    async def run():
        status, payload, ct, extra = await app.server.dispatch_with_headers(
            method, path, body, headers
        )
        if hasattr(payload, "__aiter__"):
            payload = [chunk async for chunk in payload]
        return status, payload, ct, extra

    return asyncio.run(run())


# ------------------------------------------------------------------ trace schema


def test_trace_round_trip_and_canonical_bytes(tmp_path):
    requests = [
        TraceRequest(t=0.5, prompt=(3, 1, 4), max_tokens=4, tenant="acme",
                     priority="high", deadline_ms=1500.0),
        TraceRequest(t=0.25, prompt=(9, 2), max_tokens=2, session="s0", turn=0),
        TraceRequest(t=0.75, prompt=(6,), max_tokens=2, session="s0", turn=1),
    ]
    path = str(tmp_path / "trace.ndjson")
    write_trace(path, requests, {"note": "unit"})
    meta, loaded = read_trace(path)
    assert meta == {"note": "unit"}
    # arrival-ordered, fields intact
    assert [r.t for r in loaded] == [0.25, 0.5, 0.75]
    assert loaded[1].tenant == "acme" and loaded[1].priority == "high"
    assert loaded[0].session == "s0" and loaded[2].turn == 1
    # canonical: dumping the loaded requests reproduces the file bytes
    assert dumps_trace(loaded, meta) == (tmp_path / "trace.ndjson").read_text()


def test_trace_version_and_kind_rejected():
    with pytest.raises(ValueError, match="trace_version"):
        loads_trace('{"trace_version": 99, "kind": "unionml-tpu-traffic-trace"}\n')
    with pytest.raises(ValueError, match="header"):
        loads_trace('{"hello": 1}\n')
    with pytest.raises(ValueError, match="header"):
        loads_trace("")


def test_trace_request_validation():
    with pytest.raises(ValueError, match="offset"):
        TraceRequest(t=-1.0, prompt=(1,))
    with pytest.raises(ValueError, match="route"):
        TraceRequest(t=0.0, prompt=(1,), route="/v2/everything")
    with pytest.raises(ValueError, match="session"):
        TraceRequest(t=0.0, prompt=(1,), turn=2)
    with pytest.raises(ValueError, match="prompt"):
        TraceRequest(t=0.0)


# ------------------------------------------------------------------ scenarios


def test_synthesize_same_seed_byte_identical():
    for name in SCENARIOS:
        assert synthesize_text(name, 11) == synthesize_text(name, 11), name
        assert synthesize_text(name, 11) != synthesize_text(name, 12), name


def test_synthesize_overrides_and_unknowns():
    small = synthesize("rag_long_prompt", 0, requests=3)
    assert len(small) == 3
    with pytest.raises(ValueError, match="unknown scenario"):
        synthesize("nope", 0)
    with pytest.raises(ValueError, match="params"):
        synthesize("rag_long_prompt", 0, bogus=1)
    with pytest.raises(ValueError, match="unknown scenario"):
        scenario_targets("nope")


def test_chat_multiturn_sessions_are_linked():
    requests = synthesize("chat_multiturn", 5)
    by_session = {}
    for request in requests:
        assert request.session is not None and request.turn is not None
        by_session.setdefault(request.session, []).append(request)
    for turns in by_session.values():
        assert [r.turn for r in sorted(turns, key=lambda r: r.t)] == list(range(len(turns)))


# ------------------------------------------------------------------ capture tap


def test_recorder_tap_records_openai_traffic(tiny, tmp_path):
    app, engine = _app(tiny)
    try:
        app.configure_traffic_capture(record_traffic=str(tmp_path / "cap"))
        body = json.dumps({"prompt": [3, 1, 4], "max_tokens": 2}).encode()
        _dispatch(app, "POST", "/v1/completions", body,
                  {"x-tenant-id": "acme", "x-priority": "high"})
        _dispatch(app, "POST", "/v1/completions", body)
        path = app.traffic_recorder.close()
        meta, requests = read_trace(path)
        assert meta["captured"] is True and meta["hashed_prompts"] is False
        assert len(requests) == 2
        assert requests[0].prompt == (3, 1, 4) and requests[0].max_tokens == 2
        assert requests[0].tenant == "acme" and requests[0].priority == "high"
        assert requests[1].tenant is None and requests[1].priority is None
        assert requests[1].t >= requests[0].t  # offsets from the recorder clock
        assert app.traffic_recorder.stats() == {"recorded": 2, "dropped": 0}
    finally:
        app.configure_traffic_capture(record_traffic="")
        engine.close()


def test_recorder_hashed_mode_never_writes_ids(tiny, tmp_path):
    app, engine = _app(tiny)
    try:
        app.configure_traffic_capture(record_traffic=str(tmp_path / "cap"), hash_prompts=True)
        body = json.dumps({"prompt": [7, 7, 7, 7], "max_tokens": 2}).encode()
        _dispatch(app, "POST", "/v1/completions", body)
        path = app.traffic_recorder.close()
        with open(path) as fh:
            text = fh.read()
        assert "[7," not in text and '"prompt"' not in text
        meta, requests = read_trace(path)
        assert meta["hashed_prompts"] is True
        assert requests[0].prompt is None
        assert requests[0].prompt_len == 4 and len(requests[0].prompt_sha256) == 64
        # the replayer regenerates a deterministic same-length prompt
        from unionml_tpu.workloads.replayer import _materialize_prompt

        regen = _materialize_prompt(requests[0])
        assert len(regen) == 4 and regen == _materialize_prompt(requests[0])
    finally:
        app.configure_traffic_capture(record_traffic="")
        engine.close()


def test_recorder_never_raises_into_serving(tmp_path):
    recorder = TraceRecorder(str(tmp_path / "cap"))
    recorder.close()
    recorder._handle = None
    # a closed/broken recorder counts the drop and stays quiet
    recorder.record("/v1/completions")  # no prompt/len/body -> invalid request
    assert recorder.stats()["dropped"] == 1
    set_active_traffic_recorder(None)


# ------------------------------------------------------------------ replayer


def test_replay_self_hosted_collects_per_tenant_and_verdicts(tiny):
    app, engine = _app(tiny, max_waiting=64)
    try:
        requests = [
            TraceRequest(t=0.0, prompt=(3, 1, 4), max_tokens=3, tenant="a"),
            TraceRequest(t=0.02, prompt=(9, 2, 6), max_tokens=3, tenant="b"),
            TraceRequest(t=0.04, prompt=(5, 5), max_tokens=3),  # anonymous
        ]
        targets = {"a": {"ttft_p95_ms": 60000.0, "shed_ratio": 0.01}}
        report = replay(requests, app=app, targets=targets)
        assert report["requests"] == 3 and report["ok"] == 3 and report["shed"] == 0
        assert set(report["per_tenant"]) == {"a", "b", "anonymous"}
        tenant_a = report["per_tenant"]["a"]
        assert tenant_a["tokens"] == 3 and tenant_a["ttft_ms"]["n"] == 1
        assert tenant_a["tbt_ms"]["n"] >= 1  # 3 tokens stream in >= 2 chunks
        assert report["verdicts"]["a"]["state"] == "pass"
        assert report["verdict_state"] == "pass"
        assert report["schedule"]["adherence"] == 1.0
        assert report["tokens_per_s"] > 0
    finally:
        engine.close()


def test_replay_session_turns_resend_history(tiny):
    app, engine = _app(tiny, max_waiting=64)
    try:
        requests = [
            TraceRequest(t=0.0, prompt=(3, 1), max_tokens=2, session="s", turn=0),
            TraceRequest(t=0.0, prompt=(9,), max_tokens=2, session="s", turn=1),
        ]
        report = replay(requests, app=app)
        assert report["ok"] == 2
        # turn 1's prompt = turn 0's prompt + its 2 completion tokens + the
        # new token => 6 prompt tokens total were sent on the wire; the
        # engine saw both submissions
        per = report["per_tenant"]["anonymous"]
        assert per["requests"] == 2 and per["tokens"] == 4
    finally:
        engine.close()


def test_replay_deadline_sheds_are_classified(tiny):
    app, engine = _app(tiny, max_waiting=64)
    try:
        requests = [
            TraceRequest(t=0.0, prompt=(3, 1, 4), max_tokens=2, tenant="t"),
            # born-expired deadline: the HTTP layer sheds 503 before dispatch
            TraceRequest(t=0.01, prompt=(9, 2), max_tokens=2, tenant="t",
                         deadline_ms=0.0),
        ]
        report = replay(requests, app=app)
        per = report["per_tenant"]["t"]
        assert per["shed"] == 1 and per["shed_ratio"] == 0.5
        assert report["shed"] == 1 and report["errors"] == 0
    finally:
        engine.close()


def test_replay_argument_validation(tiny):
    with pytest.raises(ValueError, match="exactly one"):
        replay([], app=object(), target="http://x")
    with pytest.raises(ValueError, match="exactly one"):
        replay([])
    with pytest.raises(ValueError, match="concurrency"):
        replay([], app=object(), concurrency=0)
    with pytest.raises(ValueError, match="rate_scale"):
        replay([], app=object(), rate_scale=0.0)


def test_report_schedule_adherence_math():
    """The adherence/lag math on synthetic records (no wall clock): requests
    within grace count, laggards don't, percentiles come from the lags."""
    records = []
    for tenant, lag in (("a", 0.0), ("a", 0.1), ("b", 0.9)):
        record = _Record(tenant)
        record.status = 200
        record.lag_s = lag
        record.ttft_s = 0.01
        record.e2e_s = 0.02
        record.tokens = 2
        records.append(record)
    report = _report(records, 2.0, grace_s=0.25, rate_scale=1.0, targets=None, meta=None)
    assert report["schedule"]["adherence"] == pytest.approx(2 / 3, abs=1e-3)
    assert report["schedule"]["lag_max_ms"] == 900.0
    assert report["tokens_per_s"] == 3.0
    assert report["per_tenant"]["a"]["requests"] == 2


# ------------------------------------------------------------------ verdict math


def test_verdict_states_and_burn_rates():
    per_tenant = {
        "good": {"requests": 10, "shed_ratio": 0.0,
                 "ttft_ms": {"n": 10, "p95_ms": 80.0}, "tbt_ms": {"n": 40, "p99_ms": 5.0}},
        "warm": {"requests": 10, "shed_ratio": 0.0,
                 "ttft_ms": {"n": 10, "p95_ms": 110.0}, "tbt_ms": {"n": 0}},
        "bad": {"requests": 10, "shed_ratio": 0.5,
                "ttft_ms": {"n": 10, "p95_ms": 500.0}, "tbt_ms": {"n": 0}},
    }
    targets = {
        "good": {"ttft_p95_ms": 100.0, "tbt_p99_ms": 10.0, "shed_ratio": 0.01},
        "warm": {"ttft_p95_ms": 100.0},
        "bad": {"ttft_p95_ms": 100.0, "shed_ratio": 0.01},
        "absent": {"ttft_p95_ms": 100.0},
    }
    verdicts = tenant_verdicts(per_tenant, targets)
    assert verdicts["good"]["state"] == "pass"
    assert verdicts["good"]["objectives"]["ttft_p95_ms"]["burn_rate"] == 0.8
    assert verdicts["warm"]["state"] == "warn"  # burn 1.1 <= warn_factor 1.2
    assert verdicts["bad"]["state"] == "breach"
    assert verdicts["bad"]["objectives"]["shed_ratio"]["burn_rate"] == 50.0
    # a promised-but-missing tenant is a breach, not a silent pass
    assert verdicts["absent"]["state"] == "breach"
    assert overall_state(verdicts) == "breach"
    assert overall_state({}) == "pass"
    # None-free (the /metrics exposition contract)
    assert "None" not in json.dumps(verdicts)


def test_verdict_min_samples_gate_and_validation():
    per_tenant = {"quiet": {"requests": 1, "shed_ratio": 0.0,
                            "ttft_ms": {"n": 1, "p95_ms": 900.0}, "tbt_ms": {"n": 0}}}
    verdicts = tenant_verdicts(per_tenant, {"quiet": {"ttft_p95_ms": 100.0}}, min_samples=3)
    assert verdicts["quiet"]["state"] == "pass"  # too little evidence to convict
    with pytest.raises(ValueError, match="warn_factor"):
        tenant_verdicts({}, {}, warn_factor=0.5)
    with pytest.raises(ValueError, match="min_samples"):
        tenant_verdicts({}, {}, min_samples=0)


# ------------------------------------------------------------- per-tenant SLOs


def test_tenant_slo_registry_bounded_lru():
    clock = [0.0]
    config = SLOConfig(ttft_p95_ms=100.0, min_samples=1)
    registry = TenantSLORegistry(lambda t: config, max_tenants=2, clock=lambda: clock[0])
    for tenant in ("a", "b", "c"):
        registry.note_ttft(tenant, None, 0.05)
    assert len(registry) == 2 and registry.evicted == 1
    assert set(registry.evaluate()) == {"b", "c"}  # "a" was least-recently-fed
    # a tenant with no armed config never creates state
    none_registry = TenantSLORegistry(lambda t: None)
    none_registry.note_ttft("x", None, 0.05)
    none_registry.shed("x")
    assert len(none_registry) == 0 and none_registry.evaluate() == {}
    registry.clear()
    assert len(registry) == 0


def test_tenant_spec_slo_config_and_validation():
    assert TenantSpec().slo_config() is None
    config = TenantSpec(slo_ttft_p95_ms=150.0, slo_shed_ratio=0.02).slo_config()
    assert config.ttft_p95_ms == 150.0 and config.shed_ratio == 0.02
    assert config.tbt_p99_ms is None and config.armed
    with pytest.raises(ValueError, match="slo_ttft_p95_ms"):
        TenantSpec(slo_ttft_p95_ms=-1.0)


def test_engine_keys_tenant_slo_and_surfaces_sections(tiny):
    registry = TenantRegistry({
        "tight": TenantSpec(slo_ttft_p95_ms=0.001),   # sub-microsecond: must breach
        "roomy": TenantSpec(slo_ttft_p95_ms=60000.0),
        "none": TenantSpec(),                          # no targets: never tracked
    })
    app, engine = _app(tiny, tenancy=registry)
    try:
        body = json.dumps({"prompt": [3, 1, 4], "max_tokens": 2}).encode()
        for tenant in ("tight", "roomy", "none"):
            # min_samples (default 3) gates breaching: give each window the
            # evidence it needs before expecting a verdict
            for _ in range(3):
                status, _, _, _ = _dispatch(
                    app, "POST", "/v1/completions", body, {"x-tenant-id": tenant}
                )
                assert status == 200
        stats = engine.stats()
        section = stats["tenant_slo"]
        assert set(section) == {"tight", "roomy"}  # target-less tenants absent
        assert section["tight"]["objectives"]["ttft_p95_ms"]["state"] == "breach"
        assert section["tight"]["breached_requests"] == 3
        assert section["roomy"]["state"] == "ok"
        assert engine.tenant_slo().keys() == section.keys()
        # /metrics carries it and the Prometheus render is None-free
        status, snapshot, _, _ = _dispatch(app, "GET", "/metrics")
        assert "tenant_slo" in snapshot["generation"]
        status, text, _, _ = _dispatch(app, "GET", "/metrics?format=prometheus")
        assert status == 200 and "tenant_slo" in text and "None" not in text
        # /healthz merges the section fleet-wide
        status, payload, _, _ = _dispatch(app, "GET", "/healthz")
        assert payload["tenant_slo"]["tight"]["state"] == "breach"
    finally:
        engine.close()


def test_engine_without_tenant_targets_stays_byte_for_byte(tiny):
    module, params = tiny
    bare = ContinuousBatcher(Generator(module, params, _cfg()), slots=1)
    registry = TenantRegistry({"plain": TenantSpec(weight=2.0)})  # no slo targets
    with_reg = ContinuousBatcher(
        Generator(module, params, _cfg()), slots=1, tenancy=registry
    )
    try:
        for chunk in bare.submit([3, 1, 4], max_new_tokens=2):
            pass
        for chunk in with_reg.submit([3, 1, 4], max_new_tokens=2, tenant="plain"):
            pass
        assert "tenant_slo" not in bare.stats()
        assert "tenant_slo" not in with_reg.stats()
        assert bare.tenant_slo() == {} and with_reg.tenant_slo() == {}
        # slo=False disables the layer with the rest of windowed telemetry
        off = ContinuousBatcher(Generator(module, params, _cfg()), slots=1, slo=False)
        try:
            assert off._tenant_slo is None and off.tenant_slo() == {}
        finally:
            off.close()
    finally:
        bare.close()
        with_reg.close()


def test_tenant_sheds_feed_tenant_slo(tiny):
    registry = TenantRegistry({
        # bucket capacity 2 (rate x burst): the 3rd and 4th requests shed
        "limited": TenantSpec(req_per_s=0.001, burst_s=2000.0, slo_shed_ratio=0.01),
    })
    module, params = tiny
    engine = ContinuousBatcher(
        Generator(module, params, _cfg()), slots=1, tenancy=registry
    )
    try:
        for _ in range(2):
            for chunk in engine.submit([3, 1], max_new_tokens=2, tenant="limited"):
                pass
        from unionml_tpu.serving.overload import TenantThrottled

        for _ in range(2):
            with pytest.raises(TenantThrottled):
                engine.submit([3, 1], max_new_tokens=2, tenant="limited")
        section = engine.tenant_slo()["limited"]
        shed = section["objectives"]["shed_ratio"]
        assert shed["state"] == "breach"  # 2 sheds / 4 arrivals >> 0.01
        assert shed["fast"]["value"] == 0.5
    finally:
        engine.close()


# ---------------------------------------------------------- tenant affinity


def test_scheduler_tenant_affinity_fallback_and_margin():
    sched = ReplicaScheduler(3, affinity_tokens=4, affinity_margin=2)
    sched.note(2, tenant="acme")
    # no prefix signal: the tenant's last replica heads the walk within margin
    order, head = sched.order([0, 0, 1], tenant="acme")
    assert order[0] == 2 and head == "tenant"
    # margin gate: a hotspot replica loses its tenant pull
    order, head = sched.order([0, 0, 9], tenant="acme")
    assert order[0] == 0 and head is False
    # an actual radix probe outranks the tenant map
    order, head = sched.order([0, 0, 1], [1, 2, 3, 4], cached=[0, 12, 0], tenant="acme")
    assert order[0] == 1 and head is True
    # radix probes present but cold for THIS prompt: tenant affinity still lands
    order, head = sched.order([0, 0, 1], [1, 2, 3, 4], cached=[0, 1, 0], tenant="acme")
    assert order[0] == 1 and head is True  # warm replica 1 wins (cached=1)
    order, head = sched.order([0, 0, 1], None, cached=[0, 0, 4], tenant="acme")
    assert order[0] == 2 and head is True
    # unknown tenants ride plain load order
    order, head = sched.order([1, 0, 2], tenant="nobody")
    assert order == [1, 0, 2] and head is False


def test_scheduler_tenant_affinity_accounting_bound_and_resize():
    sched = ReplicaScheduler(3, tenant_affinity_capacity=2)
    sched.note(1, tenant="a")
    sched.note(2, tenant="b")
    sched.note(0, tenant="c")  # evicts "a" (LRU bound)
    assert sched.stats()["tenant_affinity_entries"] == 2
    order, head = sched.order([0, 0, 0], tenant="a")
    assert head is False  # evicted: no pull left
    order, head = sched.order([1, 1, 0], tenant="b")
    assert order[0] == 2 and head == "tenant"
    sched.note(2, tenant="b", affinity=head)
    assert sched.stats()["tenant_affinity_hits"] == 1
    assert sched.stats()["affinity_hits"] == 0  # distinct counters
    # resize drops entries pointing at removed replicas (c -> 0 survives)
    sched.resize(1)
    assert sched.stats()["tenant_affinity_entries"] == 1
    order, head = sched.order([0], tenant="b")
    assert head is False  # b's replica 2 is gone
    with pytest.raises(ValueError, match="tenant_affinity_capacity"):
        ReplicaScheduler(2, tenant_affinity_capacity=0)


# ------------------------------------------------------------ stop= / logprobs


def test_openai_stop_truncates_and_reports_stop(tiny):
    app, engine = _app(tiny)
    try:
        body = json.dumps({"prompt": [3, 1, 4], "max_tokens": 8}).encode()
        status, full, _, _ = _dispatch(app, "POST", "/v1/completions", body)
        assert status == 200
        tokens = full["choices"][0]["text"].split()
        assert len(tokens) == 8
        stop_word = tokens[2]
        body = json.dumps({
            "prompt": [3, 1, 4], "max_tokens": 8, "stop": stop_word,
        }).encode()
        status, payload, _, _ = _dispatch(app, "POST", "/v1/completions", body)
        assert status == 200
        choice = payload["choices"][0]
        assert choice["finish_reason"] == "stop"
        assert stop_word not in choice["text"].split()
        assert choice["text"].split() == [t for t in tokens[:2] if t != stop_word]
        # list form + SSE leg
        body = json.dumps({
            "prompt": [3, 1, 4], "max_tokens": 8, "stop": ["zzz", stop_word],
            "stream": True,
        }).encode()
        status, chunks, ct, _ = _dispatch_stream(app, "POST", "/v1/completions", body)
        assert status == 200 and chunks[-1] == b"data: [DONE]\n\n"
        events = [json.loads(c[6:]) for c in chunks[:-1]]
        assert events[-1]["choices"][0]["finish_reason"] == "stop"
        streamed = "".join(e["choices"][0]["text"] for e in events)
        assert stop_word not in streamed.split()
    finally:
        engine.close()


def test_openai_stop_validation(tiny):
    app, engine = _app(tiny)
    try:
        for bad in ("", [], ["a", "b", "c", "d", "e"], [""], [1]):
            body = json.dumps({"prompt": [3], "stop": bad}).encode()
            status, payload, _, _ = _dispatch(app, "POST", "/v1/completions", body)
            assert status == 400 and "stop" in payload["detail"], bad
    finally:
        engine.close()


def test_openai_logprobs_completions_and_chat(tiny):
    app, engine = _app(tiny)
    try:
        body = json.dumps({"prompt": [3, 1, 4], "max_tokens": 4, "logprobs": 1}).encode()
        status, payload, _, _ = _dispatch(app, "POST", "/v1/completions", body)
        assert status == 200
        block = payload["choices"][0]["logprobs"]
        assert len(block["token_logprobs"]) == 4 == len(block["tokens"])
        assert all(lp <= 0.0 for lp in block["token_logprobs"])
        assert block["tokens"] == payload["choices"][0]["text"].split()
        # streaming: every chunk carries its tokens' logprobs
        body = json.dumps({
            "prompt": [3, 1, 4], "max_tokens": 4, "logprobs": True, "stream": True,
        }).encode()
        status, chunks, _, _ = _dispatch_stream(app, "POST", "/v1/completions", body)
        events = [json.loads(c[6:]) for c in chunks[:-1]]
        streamed = [
            lp for e in events if e["choices"][0].get("logprobs")
            for lp in e["choices"][0]["logprobs"]["token_logprobs"]
        ]
        assert len(streamed) == 4
        # chat logprobs: true
        body = json.dumps({
            "messages": [{"role": "user", "content": "hi"}],
            "max_tokens": 2, "logprobs": True,
        }).encode()
        status, payload, _, _ = _dispatch(app, "POST", "/v1/chat/completions", body)
        assert status == 400  # string prompt needs a tokenizer — unrelated to logprobs
        app.model.tokenizer = types.SimpleNamespace(
            encode=lambda text: [1 + (ord(c) % 90) for c in text][:8],
            decode=lambda ids: "".join(chr(97 + (i % 26)) for i in ids),
        )
        status, payload, _, _ = _dispatch(app, "POST", "/v1/chat/completions", body)
        assert status == 200
        content = payload["choices"][0]["logprobs"]["content"]
        assert len(content) == 2 and all("logprob" in entry for entry in content)
        del app.model.tokenizer
    finally:
        engine.close()


def test_openai_logprobs_validation(tiny):
    app, engine = _app(tiny)
    try:
        body = json.dumps({"prompt": [3], "logprobs": -1}).encode()
        status, payload, _, _ = _dispatch(app, "POST", "/v1/completions", body)
        assert status == 400 and "logprobs" in payload["detail"]
        body = json.dumps({
            "messages": [{"role": "user", "content": "x"}], "logprobs": 3,
        }).encode()
        status, payload, _, _ = _dispatch(app, "POST", "/v1/chat/completions", body)
        assert status == 400 and "logprobs" in payload["detail"]
    finally:
        engine.close()


def test_engine_logprobs_stream_and_fences(tiny):
    module, params = tiny
    engine = ContinuousBatcher(Generator(module, params, _cfg()), slots=1)
    try:
        stream = engine.submit([3, 1, 4], max_new_tokens=4, logprobs=True)
        tokens = []
        for chunk in stream:
            tokens.extend(int(t) for t in np.asarray(chunk).ravel())
            assert len(stream.logprobs) >= len(tokens)  # lp precedes its token
        assert len(stream.logprobs) == 4
        assert all(lp <= 0.0 for lp in stream.logprobs)
        # tokens are identical to a logprobs-off run (pure ride-along)
        plain = []
        for chunk in engine.submit([3, 1, 4], max_new_tokens=4):
            plain.extend(int(t) for t in np.asarray(chunk).ravel())
        assert plain == tokens
        with pytest.raises(ValueError, match="export_handoff"):
            engine.submit([3], logprobs=True, export_handoff=True)
    finally:
        engine.close()


# ------------------------------------------------------------- chaos availability


def test_availability_judgment_math():
    from unionml_tpu.workloads.verdicts import availability

    samples = [
        # tenant a: 3 ok, launched around one fault at t=1.0
        {"tenant": "a", "status": 200, "start_s": 0.2, "ttft_s": 0.05},
        {"tenant": "a", "status": 200, "start_s": 1.4, "ttft_s": 0.25},
        {"tenant": "a", "status": 200, "start_s": 2.0, "ttft_s": 0.05},
        # tenant b: one clean error (503 record) and one hang (no status)
        {"tenant": "b", "status": 200, "start_s": 0.5, "ttft_s": 0.05},
        {"tenant": "b", "status": 503, "start_s": 1.1, "ttft_s": None},
        {"tenant": "b", "status": None, "start_s": 1.2, "ttft_s": None},
    ]
    out = availability(samples, fault_times_s=[1.0], target=0.99)
    assert out["requests"] == 6 and out["ok"] == 4
    assert out["success_ratio"] == pytest.approx(4 / 6, abs=1e-4)
    assert out["clean_errors"] == 1 and out["hangs"] == 1
    assert out["clean_error_ratio"] == 0.5
    assert out["per_tenant"]["a"]["success_ratio"] == 1.0
    assert out["per_tenant"]["a"]["meets_target"] == 1
    assert out["per_tenant"]["b"]["meets_target"] == 0
    # recovery = first post-fault launch that streamed: a's t=1.4 + 0.25 TTFT
    assert out["recovery"] == [
        {"fault_t_s": 1.0, "recovered": 1, "recovery_ms": pytest.approx(650.0, abs=1.0)}
    ]
    assert out["recovery_ms_max"] == pytest.approx(650.0, abs=1.0)

    # no failures, no faults: both ratios saturate at 1.0 and recovery is absent
    clean = availability(
        [{"tenant": "a", "status": 200, "start_s": 0.0, "ttft_s": 0.01}]
    )
    assert clean["success_ratio"] == 1.0 and clean["clean_error_ratio"] == 1.0
    assert "recovery" not in clean

    # an unrecovered fault reports recovered: 0 with NO recovery_ms key
    # (absent, never None — the exposition contract)
    dark = availability(
        [{"tenant": "a", "status": 503, "start_s": 2.0, "ttft_s": None}],
        fault_times_s=[1.5],
    )
    assert dark["recovery"] == [{"fault_t_s": 1.5, "recovered": 0}]


def test_replay_report_carries_availability_when_faults_given(tiny):
    """The replay plumb: fault_times_s adds the availability section built
    from the records' real launch offsets and TTFTs."""
    from unionml_tpu.workloads import replay, synthesize

    app, engine = _app(tiny, max_waiting=64)
    try:
        requests = synthesize("chaos_fleet", 3, requests_per_tenant=2, duration_s=0.4)
        report = replay(requests, app=app, fault_times_s=[0.05])
        availability_block = report["availability"]
        assert availability_block["requests"] == len(requests)
        assert set(availability_block["per_tenant"]) == {"chaos-a", "chaos-b"}
        assert availability_block["recovery"][0]["fault_t_s"] == 0.05
    finally:
        engine.close()
