"""Grammar-constrained (structured) decoding.

Oracles, mirroring the generation ring's style (tests/unit/test_generate.py):

- compiler level: the token DFA's ``allowed``/``trans``/EOS columns are checked
  against Python ``re.fullmatch`` over enumerated token sequences — acceptance
  (EOS allowed) must equal full-match of the concatenated text, and every
  allowed token must keep the text extendable to a sentence of the language
  (token-level liveness);
- engine level: greedy/sampled decoding under a constraint must emit text the
  grammar full-matches (or a legal prefix when the budget truncates), the FREE
  grammar must be byte-identical to an unconstrained generator, and the
  continuous batcher's concurrent constrained streams must equal their solo
  ``Generator.__call__(constraint=...)`` runs token-exactly.

The reference has no generation surface at all (SURVEY.md §2.3); structured
output is new TPU-native capability: the grammar is data (device tables), not
control flow, so one compiled decode program serves every grammar.
"""

import re
from typing import List

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from unionml_tpu.models import (
    ConstraintSet,
    DraftSpec,
    GenerationConfig,
    Generator,
    Llama,
    LlamaConfig,
    TokenConstraint,
    compile_regex,
    literal_choice,
)

EOS = 96


def _texts() -> List[str]:
    """Token id -> decoded text for the tiny vocab: ids 1-26 = a-z, 27-36 =
    digits, a few multi-char BPE-style pieces, everything else (incl. pad 0 and
    eos 96) decodes empty."""
    texts = [""] * 97
    for i in range(26):
        texts[1 + i] = chr(ord("a") + i)
    for i in range(10):
        texts[27 + i] = str(i)
    texts[40], texts[41], texts[42] = "ab", "12", "3.5"
    return texts


TEXTS = _texts()


@pytest.fixture(scope="module")
def tiny():
    config = LlamaConfig.tiny(
        vocab_size=97, dim=64, n_layers=2, n_heads=4, n_kv_heads=2, hidden_dim=128,
        dtype=jnp.float32, param_dtype=jnp.float32,
    )
    module = Llama(config)
    params = module.init(jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))["params"]
    return module, params, config


def decode_text(row, texts=TEXTS) -> str:
    out = ""
    for t in np.asarray(row).tolist():
        if t == EOS:
            break
        out += texts[t]
    return out


# ---------------------------------------------------------------------- compiler


def test_token_dfa_acceptance_equals_re_fullmatch():
    """Walk every token sequence up to depth 3 over a small vocab: the DFA must
    allow exactly the extendable ones, and allow EOS exactly at full matches."""
    vocab = ["", "a", "b", "ab", "c", "cc"]
    pattern = r"(ab|b)*c{1,2}"
    c = compile_regex(pattern, vocab, eos_id=0)
    alphabet = "abc"

    # brute-force the language up to 8 chars (regular + short)
    def strings(prefix, depth):
        yield prefix
        if depth == 0:
            return
        for ch in alphabet:
            yield from strings(prefix + ch, depth - 1)
    lang = {s for s in strings("", 8) if re.fullmatch(pattern, s)}

    def extendable(text: str) -> bool:
        return any(s.startswith(text) for s in lang)

    seqs = [((), 0, "")]
    for _ in range(3):
        nxt = []
        for toks, state, text in seqs:
            # EOS column == exact acceptance
            assert bool(c.allowed[state, 0]) == bool(re.fullmatch(pattern, text)), (toks, text)
            for t, tx in enumerate(vocab):
                if t == 0:
                    continue
                ok = bool(c.allowed[state, t])
                assert ok == extendable(text + tx), (text, tx)
                if ok:
                    nxt.append((toks + (t,), int(c.trans[state, t]), text + tx))
        seqs = nxt


def test_empty_match_allows_immediate_eos():
    c = compile_regex(r"(ab)*", ["", "ab"], eos_id=0)
    assert bool(c.allowed[0, 0])


def test_bounded_quantifier():
    c = compile_regex("a{2,3}", ["", "a", "aa"], eos_id=0)
    s1 = int(c.trans[0, 1])
    assert not c.allowed[s1, 0]  # "a": not yet a sentence
    s2 = int(c.trans[s1, 1])
    assert c.allowed[s2, 0]  # "aa"
    s3 = int(c.trans[s2, 1])
    assert c.allowed[s3, 0] and not c.allowed[s3, 1]  # "aaa" is maximal
    # the two-char token takes the same states
    assert int(c.trans[0, 2]) == s2


def test_char_classes_and_escapes():
    vocab = ["", "a", "Z", "_", "7", " ", "-"]
    c = compile_regex(r"\w+", vocab, eos_id=0)
    for t in (1, 2, 3, 4):
        assert c.allowed[0, t]
    for t in (5, 6):
        assert not c.allowed[0, t]
    neg = compile_regex(r"[^0-9]+", vocab, eos_id=0)
    assert neg.allowed[0, 1] and not neg.allowed[0, 4]


def test_literal_choice_tokenization_paths():
    vocab = ["", "y", "es", "yes", "n", "o", "no", "s"]
    c = literal_choice(["yes", "no"], vocab, eos_id=0)
    start_ok = {vocab[t] for t in range(len(vocab)) if c.allowed[0, t]}
    assert start_ok == {"y", "yes", "n", "no"}
    s_yes = int(c.trans[0, 3])
    assert c.allowed[s_yes, 0]  # complete
    assert not c.allowed[s_yes, 7]  # "yess" escapes the language


def test_malformed_brace_is_literal_like_re():
    """``re`` treats non-quantifier braces as literals; the compiler must not
    silently parse them as quantifiers (a{-2} once compiled to the
    empty-string language)."""
    vocab = ["", "a", "{", "-", "2", "}", " ", ",", "3", "4"]
    for pat in ("a{-2}", "a{ 2}", "a{}", "a{2,3,4}"):
        c = compile_regex(pat, vocab, eos_id=0)
        state = 0
        for ch in pat:
            t = vocab.index(ch)
            assert c.allowed[state, t], (pat, ch)
            state = int(c.trans[state, t])
        assert c.allowed[state, 0], pat  # the literal text is a full match
        assert re.fullmatch(re.escape(pat) if False else pat, pat), pat


def test_open_ended_brace_quantifiers():
    vocab = ["", "a"]
    c = compile_regex("a{,2}", vocab, eos_id=0)  # 0-2 a's
    assert c.allowed[0, 0]
    s1 = int(c.trans[0, 1])
    s2 = int(c.trans[s1, 1])
    assert c.allowed[s2, 0] and not c.allowed[s2, 1]
    # Python 3.12 treats bare {,} as {0,}
    c = compile_regex("a{,}", vocab, eos_id=0)
    assert c.allowed[0, 0]
    s = int(c.trans[0, 1])
    assert c.allowed[s, 0] and c.allowed[s, 1]


def test_dangling_escape_in_class_raises_valueerror():
    with pytest.raises(ValueError, match="dangling backslash"):
        compile_regex("[\\", ["", "a"], eos_id=0)
    with pytest.raises(ValueError, match="quantifier bounds"):
        compile_regex("a{3,2}", ["", "a"], eos_id=0)


def test_unrealizable_grammar_raises():
    with pytest.raises(ValueError, match="unreachable with this vocabulary"):
        compile_regex("[0-9]+", ["", "a", "b"], eos_id=0)


def test_empty_string_tokens_never_allowed():
    c = compile_regex("a*", ["", "a", ""], eos_id=0)
    assert not c.allowed[:, 2].any()


def test_anchors_are_noops_under_fullmatch():
    """``^[ab]+$`` — the most common full-match spelling — must compile to the
    same language as ``[ab]+``, not demand literal '^'/'$' characters."""
    vocab = ["", "a", "b", "^", "$"]
    c = compile_regex(r"^[ab]+$", vocab, eos_id=0)
    assert c.allowed[0, 1] and c.allowed[0, 2]
    assert not c.allowed[0, 3] and not c.allowed[0, 4]  # no literal anchors
    s = int(c.trans[0, 1])
    assert c.allowed[s, 0]  # "a" is a full match
    # redundant / repeated anchors and top-level per-branch anchors, as re allows
    for pat, tok in ((r"^^a$$", 1), (r"^a|b$", 1), (r"^a|^b", 1)):
        c = compile_regex(pat, vocab, eos_id=0)
        st = int(c.trans[0, tok])
        assert c.allowed[st, 0], pat


def test_mid_pattern_anchor_raises_escaped_is_literal():
    """Anchors anywhere but top-level pattern edges are parse errors: mid-branch
    they match nothing under fullmatch, and at GROUP branch edges (`(a$)b`,
    `a(^b)`) a no-op would silently accept strings re.fullmatch rejects."""
    for pat in (r"a^b", r"a$b", r"a+$b", r"(a$)b", r"a(^b)", r"(^a)b", r"(^a)|(b$)"):
        with pytest.raises(ValueError, match="anchor"):
            compile_regex(pat, ["", "a", "b"], eos_id=0)
    vocab = ["", "a", "^", "$"]
    c = compile_regex(r"\^a\$", vocab, eos_id=0)  # escaped = literal, as before
    s = int(c.trans[0, 2])
    s = int(c.trans[s, 1])
    s = int(c.trans[s, 3])
    assert c.allowed[s, 0]
    assert re.fullmatch(r"\^a\$", "^a$")


def test_json_object_grammar():
    import json as jsonlib

    from unionml_tpu.models import json_object

    chars = sorted(set('abcdefghijklmnopqrstuvwxyz0123456789"{}:,.-+eE \t\ntruefalsnul'))
    vocab = [""] + chars
    g = json_object({"name": "string", "age": "integer", "ok": "boolean"}, vocab, eos_id=0)

    def accepts(text: str) -> bool:
        st = 0
        for ch in text:
            t = vocab.index(ch)
            if not g.allowed[st, t]:
                return False
            st = int(g.trans[st, t])
        return bool(g.allowed[st, 0])

    good = '{"name": "ada", "age": 36, "ok": true}'
    assert accepts(good) and jsonlib.loads(good)["age"] == 36
    assert accepts('{"name":"x","age":0,"ok":false}')  # minimal whitespace
    assert not accepts('{"name": "ada"}')  # missing keys
    assert not accepts('{"age": 36, "name": "ada", "ok": true}')  # wrong order
    assert not accepts('{"name": "ada", "age": 01, "ok": true}')  # leading zero
    with pytest.raises(ValueError, match="non-empty"):
        json_object({}, vocab, eos_id=0)
    with pytest.raises(ValueError, match="JSON escaping"):
        json_object({'a"b': "string"}, vocab, eos_id=0)
    with pytest.raises(ValueError, match="unknown value type"):
        json_object({"ok": "bool"}, vocab, eos_id=0)  # typo for 'boolean'


def test_vocab_from_tokenizer_gpt2_bpe(tmp_path):
    """An offline GPT2-style BPE tokenizer round-trips through the extracted
    vocab: joining per-id texts over encode(s) reproduces s (the property the
    grammar compiler needs)."""
    import json as jsonlib

    transformers = pytest.importorskip("transformers")

    vocab = {"<|endoftext|>": 0, "a": 1, "b": 2, "ab": 3, "Ġ": 4, "Ġa": 5,
             "c": 6, "1": 7, "2": 8, "12": 9}
    (tmp_path / "vocab.json").write_text(jsonlib.dumps(vocab))
    (tmp_path / "merges.txt").write_text("#version: 0.2\na b\nĠ a\n1 2\n")
    tok = transformers.GPT2Tokenizer(str(tmp_path / "vocab.json"), str(tmp_path / "merges.txt"))

    from unionml_tpu.models import compile_regex, vocab_from_tokenizer

    texts = vocab_from_tokenizer(tok)
    assert texts[0] == ""  # special token masked out
    assert texts[4] == " " and texts[5] == " a"  # BPE space marker decoded
    s = "ab a12"
    ids = tok.encode(s, add_special_tokens=False)
    assert "".join(texts[t] for t in ids) == s

    # and the extracted vocab drives the compiler: 'ab' reachable, digits too
    c = compile_regex(r"(ab)+ a[0-9]+", texts, eos_id=0)
    st = 0
    for t in ids:  # "ab" " a" "12" spells a sentence of the language
        assert c.allowed[st, t]
        st = int(c.trans[st, t])
    assert c.allowed[st, 0]


def test_vocab_from_tokenizer_sentencepiece_space():
    """transformers' sentencepiece detok strips a word-initial ▁'s space when
    the token is first in the sequence — per-id extraction makes EVERY token
    first, which would silently drop all inter-word spaces. The extractor must
    re-prepend it."""
    from unionml_tpu.models import vocab_from_tokenizer

    class FakeSP:
        vocab_size = 5
        all_special_ids = [0]
        added_tokens_encoder = {}
        _toks = {0: "<s>", 1: "▁the", 2: "ing", 3: "▁", 4: "a"}

        def convert_ids_to_tokens(self, i):
            return self._toks[i]

        def convert_tokens_to_string(self, tokens):
            # mimic LlamaTokenizer: strip the FIRST token's leading ▁
            first = tokens[0]
            if first.startswith("▁"):
                first = first[1:]
            return first + "".join(t.replace("▁", " ") for t in tokens[1:])

    texts = vocab_from_tokenizer(FakeSP())
    assert texts == ["", " the", "ing", " ", "a"]


def test_constraint_set_layout():
    vocab = ["", "a", "b"]
    g1 = compile_regex("a+", vocab, eos_id=0)
    g2 = compile_regex("b+", vocab, eos_id=0)
    cs = ConstraintSet([g1, g2])
    assert cs.n_grammars == 3  # FREE + 2
    assert bool(cs.allowed[0].all())  # FREE allows everything
    s = int(cs.starts[1])
    assert cs.allowed[s, 1] and not cs.allowed[s, 2]
    s = int(cs.starts[2])
    assert cs.allowed[s, 2] and not cs.allowed[s, 1]
    with pytest.raises(ValueError, match="grammar id"):
        cs.start_states([3])
    with pytest.raises(ValueError, match="share one vocab"):
        ConstraintSet([g1, compile_regex("a", ["", "a"], eos_id=0)])


# ------------------------------------------------------------------- generator


@pytest.fixture(scope="module")
def cs():
    return ConstraintSet(
        [
            compile_regex(r"[a-c]{3,5}", TEXTS, eos_id=EOS),
            compile_regex(r"-?[0-9]+(\.[0-9]+)?", TEXTS, eos_id=EOS),
        ]
    )


def test_greedy_generation_satisfies_grammar(tiny, cs):
    module, params, _ = tiny
    gen = Generator(
        module, params,
        GenerationConfig(max_new_tokens=10, temperature=0.0, eos_id=EOS,
                         prompt_buckets=(8,), constraints=cs),
    )
    out = gen([[3, 14, 15], [7, 7, 9]], constraint=[1, 2])
    text0, text1 = decode_text(out[0]), decode_text(out[1])
    assert re.fullmatch(r"[a-c]{3,5}", text0), text0
    # the digit grammar may be budget-truncated: full match or legal prefix
    assert re.fullmatch(r"-?[0-9]+(\.[0-9]+)?", text1) or re.fullmatch(
        r"-?[0-9]*(\.[0-9]*)?", text1
    ), text1


def test_free_grammar_matches_unconstrained(tiny, cs):
    module, params, _ = tiny
    kw = dict(max_new_tokens=8, temperature=0.0, eos_id=EOS, prompt_buckets=(8,))
    gen_cs = Generator(module, params, GenerationConfig(constraints=cs, **kw))
    gen_plain = Generator(module, params, GenerationConfig(**kw))
    prompts = [[5, 6, 7], [1, 2]]
    assert np.array_equal(gen_cs(prompts), gen_plain(prompts))
    assert np.array_equal(gen_cs(prompts, constraint=0), gen_plain(prompts))


def test_sampled_generation_satisfies_grammar(tiny, cs):
    module, params, _ = tiny
    gen = Generator(
        module, params,
        GenerationConfig(max_new_tokens=12, temperature=1.0, eos_id=EOS,
                         prompt_buckets=(8,), constraints=cs),
    )
    for seed in range(4):
        text = decode_text(gen([[2, 3]], seed=seed, constraint=1)[0])
        assert re.fullmatch(r"[a-c]{3,5}", text) or (
            len(text) < 3 and all(ch in "abc" for ch in text)
        ), (seed, text)


def test_stream_matches_call_constrained(tiny, cs):
    module, params, _ = tiny
    gen = Generator(
        module, params,
        GenerationConfig(max_new_tokens=9, temperature=0.0, eos_id=EOS,
                         prompt_buckets=(8,), constraints=cs),
    )
    prompts = [[3, 14, 15], [7, 9]]
    ref = gen(prompts, constraint=[1, 2])
    chunks = list(gen.stream(prompts, chunk_size=3, constraint=[1, 2]))
    got = np.concatenate(chunks, axis=1)
    assert np.array_equal(got, ref[:, : got.shape[1]])


def test_prefix_cache_composes_with_constraint(tiny, cs):
    module, params, _ = tiny
    gen = Generator(
        module, params,
        GenerationConfig(max_new_tokens=6, temperature=0.0, eos_id=EOS,
                         prompt_buckets=(8,), constraints=cs),
    )
    prefix = gen.cache_prefix([11, 12, 13])
    out = gen([[3, 14]], prefix=prefix, constraint=1)
    full = gen([[11, 12, 13, 3, 14]], constraint=1)
    assert np.array_equal(out, full)


def test_int8_quantized_generation_composes_with_constraints(tiny, cs):
    """Weight-only int8 x grammar: the mask applies to logits after the
    dequant-fused forward, so quantized constrained outputs still satisfy the
    grammar (exact token equality with bf16 is not expected — quantization
    legitimately perturbs logits)."""
    module, params, _ = tiny
    gen = Generator(
        module, params,
        GenerationConfig(max_new_tokens=10, temperature=0.0, eos_id=EOS,
                         prompt_buckets=(8,), constraints=cs),
        quantize="int8",
    )
    text = decode_text(gen([[3, 14, 15]], constraint=1)[0])
    # binding: the DFA forbids eos before 3 chars and forces it by 5, and the
    # 10-token budget always covers 5 single-char tokens — a correct run MUST
    # full-match (a prefix fallback would also accept an early-eos mask bug)
    assert re.fullmatch(r"[a-c]{3,5}", text), text


def test_constraint_without_set_raises(tiny):
    module, params, _ = tiny
    gen = Generator(module, params, GenerationConfig(max_new_tokens=4, prompt_buckets=(8,)))
    with pytest.raises(ValueError, match="requires GenerationConfig.constraints"):
        gen([[1, 2]], constraint=1)


def test_wrong_constraint_arity_raises(tiny, cs):
    module, params, _ = tiny
    gen = Generator(
        module, params,
        GenerationConfig(max_new_tokens=4, prompt_buckets=(8,), constraints=cs),
    )
    with pytest.raises(ValueError, match="entries for"):
        gen([[1, 2]], constraint=[1, 2])




MICRO_TEXTS = ["", "a", "b", "c", "d", ""]  # ids 1-4 decode a-d; 5 = eos
MICRO_EOS = 5


def _micro_cs(pattern: str) -> ConstraintSet:
    return ConstraintSet([compile_regex(pattern, MICRO_TEXTS, eos_id=MICRO_EOS)])


def _constrained_brute_force(module, params, cset, grammar, prompt, steps):
    """Enumerate every DFA-legal continuation (eos freezes the row; pads
    after), scoring with the CONSTRAINED policy: logits masked by the state's
    allowed set, then log-renormalized — exactly beam_fn's logprobs. Walks
    the ConstraintSet's union table from the grammar's start state."""
    import itertools

    best, best_score = None, -np.inf
    for cont in itertools.product(range(len(MICRO_TEXTS)), repeat=steps):
        tokens, score, finished, legal = list(prompt), 0.0, False, True
        state = int(cset.starts[grammar])
        for t in cont:
            if finished:
                legal = t == 0  # pad after eos
                if not legal:
                    break
                continue
            if not cset.allowed[state, t]:
                legal = False
                break
            logits = module.apply({"params": params}, jnp.asarray([tokens], jnp.int32))
            row = np.asarray(logits[0, -1], np.float64)
            row[~np.asarray(cset.allowed[state], bool)] = -np.inf
            m = row.max()
            lp = row - (np.log(np.sum(np.exp(row - m))) + m)
            score += float(lp[t])
            state = int(cset.trans[state, t])
            tokens.append(t)
            if t == MICRO_EOS:
                finished = True
        if legal and score > best_score:
            best, best_score = list(cont), score
    return best, best_score


@pytest.mark.slow  # brute-force V^steps oracle, ~28s — outside the tier-1 budget
def test_constrained_full_width_beam_equals_exhaustive(micro_lm):
    module, params, _ = micro_lm
    steps = 3
    cset = _micro_cs("[a-c]{2,3}")
    gen = Generator(
        module, params,
        GenerationConfig(max_new_tokens=steps, temperature=0.0, eos_id=MICRO_EOS,
                         prompt_buckets=(8,), constraints=cset),
    )
    for prompt in ([1, 4, 2], [3, 2]):
        best, _ = _constrained_brute_force(module, params, cset, 1, prompt, steps)
        out = gen.beam_search([prompt], num_beams=len(MICRO_TEXTS) ** (steps - 1), constraint=1)
        assert out[0].tolist() == best, (prompt, best)
        # and the winner spells a sentence (or budget-truncated prefix) of the language
        text = "".join(MICRO_TEXTS[t] for t in out[0] if t not in (0, MICRO_EOS))
        assert re.fullmatch(r"[a-c]{2,3}", text) or (len(text) <= 3 and all(ch in "abc" for ch in text))


def test_constrained_beam_one_equals_greedy(micro_lm):
    module, params, _ = micro_lm
    cset = _micro_cs("[a-c]{2,4}")
    gen = Generator(
        module, params,
        GenerationConfig(max_new_tokens=6, temperature=0.0, eos_id=MICRO_EOS,
                         prompt_buckets=(8,), constraints=cset),
    )
    prompts = [[1, 2, 3], [4, 2]]
    greedy = gen(prompts, constraint=[1, 1])
    beam = gen.beam_search(prompts, num_beams=1, constraint=[1, 1])
    assert np.array_equal(beam, greedy)


def test_constrained_beam_free_grammar_matches_unconstrained(micro_lm):
    module, params, _ = micro_lm
    cset = _micro_cs("[a-c]+")
    kw = dict(max_new_tokens=5, temperature=0.0, prompt_buckets=(8,))
    gen_cs = Generator(module, params, GenerationConfig(constraints=cset, **kw))
    gen_plain = Generator(module, params, GenerationConfig(**kw))
    prompts = [[1, 2], [3]]
    assert np.array_equal(
        gen_cs.beam_search(prompts, num_beams=3, constraint=0),
        gen_plain.beam_search(prompts, num_beams=3),
    )


def test_stop_sequences_automaton_matches_re_search():
    """Property check vs re.search over all token sequences up to depth 4: a
    walk is allowed exactly while no stop string has completed strictly inside
    an emitted token, and the must-EOS state is entered exactly when the text
    ends with a stop."""
    from unionml_tpu.models import stop_sequences

    vocab = ["", "a", "b", "ab", "ba", "bb"]
    stops = ["abb", "bb"]
    c = stop_sequences(stops, vocab, eos_id=0)

    def ends_with_stop(text):
        return any(text.endswith(s) for s in stops)

    def contains_stop_inside(prev, tok):
        # a stop completing strictly before the token's last char
        text = prev + tok
        for i in range(len(prev) + 1, len(text)):
            if any(text[:i].endswith(s) for s in stops):
                return True
        return False

    frontier = [(0, "")]
    for _ in range(4):
        nxt = []
        for state, text in frontier:
            at_stop = ends_with_stop(text)
            for t in range(1, len(vocab)):
                ok = bool(c.allowed[state, t])
                if at_stop:
                    assert not ok, (text, vocab[t])
                    continue
                expected = not contains_stop_inside(text, vocab[t])
                assert ok == expected, (text, vocab[t])
                if ok:
                    nxt.append((int(c.trans[state, t]), text + vocab[t]))
            assert bool(c.allowed[state, 0])  # eos always available
        frontier = nxt


def test_stop_sequences_end_generation(tiny):
    """Engine-level: with a stop constraint, greedy output either ends with the
    stop string (followed by eos) or never contains it."""
    from unionml_tpu.models import stop_sequences

    module, params, _ = tiny
    stops = ["ab", "ca"]
    cset = ConstraintSet([stop_sequences(stops, TEXTS, eos_id=EOS)])
    gen = Generator(
        module, params,
        GenerationConfig(max_new_tokens=12, temperature=0.0, eos_id=EOS,
                         prompt_buckets=(8,), constraints=cset),
    )
    for seed_prompt in ([3, 14, 15], [1, 2], [7, 9]):
        row = gen([seed_prompt], constraint=1)[0].tolist()
        text, hit_eos, n_emitted = "", False, 0
        for t in row:
            n_emitted += 1
            if t == EOS:
                hit_eos = True
                break
            text += TEXTS[t]
        occurrences = [i for s in stops for i in range(len(text)) if text[i:].startswith(s)]
        if any(text.endswith(s) for s in stops):
            # stop completed -> eos is FORCED on the very next step (only a
            # budget that ran out exactly at the stop's last token excuses it)
            assert hit_eos or n_emitted == 12, (text, row)
            # and the stop appears ONLY at the very end
            assert all(i + len(s) >= len(text) for s in stops for i in occurrences if text[i:].startswith(s))
        else:
            assert not occurrences, text


# -------------------------------------------------- speculative composition


def _draft_pair(tiny):
    """A half-trained 'draft': same architecture, different init — realistic
    imperfect agreement with the target."""
    module, params, _ = tiny
    d_params = module.init(jax.random.PRNGKey(7), jnp.zeros((1, 8), jnp.int32))["params"]
    return module, d_params


def test_speculative_constrained_greedy_equals_target_only(tiny, cs):
    """The composition oracle: greedy speculative decoding under a grammar is
    token-exact against the constrained PLAIN Generator — the draft can change
    speed, never tokens, constrained or not."""
    module, params, _ = tiny
    d_module, d_params = _draft_pair(tiny)
    plain = Generator(
        module, params,
        GenerationConfig(max_new_tokens=10, temperature=0.0, eos_id=EOS,
                         prompt_buckets=(8,), constraints=cs),
    )
    spec = Generator(
        module, params,
        GenerationConfig(
            max_new_tokens=10, temperature=0.0, eos_id=EOS, prompt_buckets=(8,),
            constraints=cs, draft=DraftSpec(module=d_module, params=d_params, gamma=3),
        ),
    )
    prompts = [[3, 14, 15], [7, 7, 9]]
    for gids in ([1, 2], [2, 1], [0, 1]):
        assert np.array_equal(spec(prompts, constraint=gids), plain(prompts, constraint=gids)), gids


def test_speculative_constrained_sampled_satisfies_grammar(tiny, cs):
    module, params, _ = tiny
    d_module, d_params = _draft_pair(tiny)
    spec = Generator(
        module, params,
        GenerationConfig(
            max_new_tokens=12, temperature=1.0, eos_id=EOS, prompt_buckets=(8,),
            constraints=cs, draft=DraftSpec(module=d_module, params=d_params, gamma=3),
        ),
    )
    for seed in range(3):
        text = decode_text(spec([[2, 3]], seed=seed, constraint=1)[0])
        assert re.fullmatch(r"[a-c]{3,5}", text) or (
            len(text) < 3 and all(ch in "abc" for ch in text)
        ), (seed, text)


def test_speculative_constrained_stream_matches_call(tiny, cs):
    """The draft path's stream() must thread constraint= too: per-row ragged
    chunks concatenate to exactly __call__'s emitted tokens."""
    module, params, _ = tiny
    d_module, d_params = _draft_pair(tiny)
    spec = Generator(
        module, params,
        GenerationConfig(
            max_new_tokens=9, temperature=0.0, eos_id=EOS, prompt_buckets=(8,),
            constraints=cs, draft=DraftSpec(module=d_module, params=d_params, gamma=3),
        ),
    )
    prompts = [[3, 14, 15], [7, 9]]
    ref = spec(prompts, constraint=[1, 2])
    rows = [[] for _ in prompts]
    for chunk in spec.stream(prompts, chunk_size=3, constraint=[1, 2]):
        for i, arr in enumerate(chunk):
            rows[i].extend(int(t) for t in arr)
    for i, got in enumerate(rows):
        assert got == ref[i, : len(got)].tolist(), i
        # stream stops at eos; __call__ pads the remainder
        assert all(int(t) == 0 for t in ref[i, len(got) :]), i


def test_speculative_constrained_composes_with_prefix(tiny, cs):
    """The full matrix cell: draft x grammar x shared system prompt."""
    module, params, _ = tiny
    d_module, d_params = _draft_pair(tiny)
    spec = Generator(
        module, params,
        GenerationConfig(
            max_new_tokens=6, temperature=0.0, eos_id=EOS, prompt_buckets=(8,),
            constraints=cs, draft=DraftSpec(module=d_module, params=d_params, gamma=2),
        ),
    )
    prefix = spec.cache_prefix([11, 12, 13])
    out = spec([[3, 14]], prefix=prefix, constraint=1)
    full = spec([[11, 12, 13, 3, 14]], constraint=1)
    assert np.array_equal(out, full)


@pytest.mark.parametrize("paged", [False, True], ids=["dense", "paged"])
def test_continuous_speculative_constrained_matches_solo(tiny, cs, paged):
    """The last matrix cell: concurrent speculative streams with per-request
    grammars through the shared batcher equal their solo constrained runs."""
    from unionml_tpu.serving import ContinuousBatcher

    module, params, _ = tiny
    d_module, d_params = _draft_pair(tiny)
    gen = Generator(
        module, params,
        GenerationConfig(
            max_new_tokens=8, temperature=0.0, eos_id=EOS, prompt_buckets=(8,),
            constraints=cs, draft=DraftSpec(module=d_module, params=d_params, gamma=3),
        ),
    )
    prompts = [[3, 14, 15], [7, 7, 9], [1, 2]]
    gids = [1, 2, 0]
    solo = [_solo_until_eos(gen, p, g) for p, g in zip(prompts, gids)]
    batcher = ContinuousBatcher(
        gen, slots=2, decode_chunk=2, **(dict(block_size=4) if paged else {})
    )
    try:
        streams = [batcher.submit(p, constraint=g) for p, g in zip(prompts, gids)]
        for got_stream, ref in zip(streams, solo):
            assert _collect(got_stream) == ref
    finally:
        batcher.close()


# ------------------------------------------------------------------ continuous


def _collect(stream) -> List[int]:
    return [int(t) for chunk in stream for t in np.atleast_1d(chunk)]


def _solo_until_eos(gen, prompt, gid, prefix=None) -> List[int]:
    row = gen([prompt], constraint=gid, prefix=prefix)[0].tolist()
    out = []
    for t in row:
        out.append(t)
        if t == EOS:
            break
    return out


@pytest.mark.parametrize("paged", [False, True], ids=["dense", "paged"])
def test_continuous_constrained_streams_match_solo(tiny, cs, paged):
    from unionml_tpu.serving import ContinuousBatcher

    module, params, _ = tiny
    gen = Generator(
        module, params,
        GenerationConfig(max_new_tokens=8, temperature=0.0, eos_id=EOS,
                         prompt_buckets=(8,), constraints=cs),
    )
    prompts = [[3, 14, 15], [7, 7, 9], [1, 2]]
    gids = [1, 2, 0]
    solo = [_solo_until_eos(gen, p, g) for p, g in zip(prompts, gids)]
    batcher = ContinuousBatcher(
        gen, slots=2, decode_chunk=2, **(dict(block_size=4) if paged else {})
    )
    try:
        # more streams than slots: admission contention + slot reuse under
        # per-request grammars
        streams = [batcher.submit(p, constraint=g) for p, g in zip(prompts, gids)]
        for got_stream, ref in zip(streams, solo):
            assert _collect(got_stream) == ref
        # /metrics telemetry: one submission per grammar id recorded
        assert batcher.stats()["grammar_submissions"] == {0: 1, 1: 1, 2: 1}
    finally:
        batcher.close()


def test_continuous_constraint_survives_preemption(tiny, cs):
    """A preempted constrained request must resume masking at the DFA state its
    echo reached (the host walk in _admit_pending), not restart the grammar."""
    from unionml_tpu.serving import ContinuousBatcher

    module, params, _ = tiny
    gen = Generator(
        module, params,
        GenerationConfig(max_new_tokens=8, temperature=0.0, eos_id=EOS,
                         prompt_buckets=(8,), constraints=cs),
    )
    prompts = [[3, 14, 15], [7, 7, 9]]
    gids = [1, 2]
    solo = [_solo_until_eos(gen, p, g) for p, g in zip(prompts, gids)]
    # a pool sized for ONE worst-case request forces the second admission to
    # wait and residents to preempt under growth pressure
    batcher = ContinuousBatcher(gen, slots=2, decode_chunk=2, block_size=2, pool_blocks=9)
    try:
        streams = [batcher.submit(p, constraint=g) for p, g in zip(prompts, gids)]
        for got_stream, ref in zip(streams, solo):
            assert _collect(got_stream) == ref
    finally:
        batcher.close()


def test_continuous_engine_death_mid_admission_errors_the_stream(tiny):
    """A session popped from pending but not yet resident is reachable by
    NEITHER of the engine's death handlers — an engine-fatal crash during its
    admission must error its stream, not strand its consumer forever (found
    live: a constrained-draft prefill crash hung the submitting thread)."""
    from unionml_tpu.serving import ContinuousBatcher

    module, params, _ = tiny
    gen = Generator(
        module, params,
        GenerationConfig(max_new_tokens=4, temperature=0.0, eos_id=EOS, prompt_buckets=(8,)),
    )
    batcher = ContinuousBatcher(gen, slots=1)

    def boom(*a, **k):
        raise RuntimeError("injected engine-fatal admission failure")

    batcher._prefill_row = boom
    stream = batcher.submit([1, 2])
    with pytest.raises(RuntimeError, match="injected"):
        next(iter(stream))
    batcher.close()


def test_everything_composes_at_once(tiny, cs):
    """The capstone: int8 weights + int8 KV cache + paged block pool + shared
    system-prompt prefix + speculative decoding + per-request grammars, all in
    one continuously-batched engine — every concurrent stream token-exact
    against its solo run through the same maximal config."""
    from unionml_tpu.serving import ContinuousBatcher

    module, params, _ = tiny
    d_module, d_params = _draft_pair(tiny)
    gen = Generator(
        module, params,
        GenerationConfig(
            max_new_tokens=8, temperature=0.0, eos_id=EOS, prompt_buckets=(8,),
            kv_cache_dtype="int8", constraints=cs,
            draft=DraftSpec(module=d_module, params=d_params, gamma=2),
        ),
        quantize="int8",
    )
    prefix = gen.cache_prefix([11, 12, 13])
    prompts = [[3, 14, 15], [7, 7, 9], [1, 2]]
    gids = [1, 2, 0]
    solo = [_solo_until_eos(gen, p, g, prefix=prefix) for p, g in zip(prompts, gids)]
    batcher = ContinuousBatcher(gen, slots=2, decode_chunk=2, prefix=prefix, block_size=4)
    try:
        streams = [batcher.submit(p, constraint=g) for p, g in zip(prompts, gids)]
        for got_stream, ref, g in zip(streams, solo, gids):
            got = _collect(got_stream)
            assert got == ref, (g, got, ref)
            if g == 1:
                text = decode_text(got)
                assert re.fullmatch(r"[a-c]{3,5}", text) or (
                    len(text) < 3 and all(c in "abc" for c in text)
                ), text
    finally:
        batcher.close()


@pytest.mark.parametrize("seed", [42, 7, 1234])
def test_continuous_randomized_stress_matches_solo(tiny, cs, seed):
    """Seeded randomized stress: a dozen streams with random prompts, lengths,
    budgets, and grammar ids through a small paged pool (preemption-prone) —
    every stream token-exact against its solo (prompt, grammar, budget) run.
    Broadens the targeted oracles to arbitrary mixes (budget x grammar
    truncation, bucket variety, slot churn); three seeds soak different
    admission/preemption interleavings."""
    from unionml_tpu.serving import ContinuousBatcher

    module, params, _ = tiny
    rng = np.random.default_rng(seed)
    gen = Generator(
        module, params,
        GenerationConfig(max_new_tokens=8, temperature=0.0, eos_id=EOS,
                         prompt_buckets=(8,), constraints=cs),
    )
    jobs = []
    for _ in range(12):
        plen = int(rng.integers(1, 8))
        prompt = [int(t) for t in rng.integers(1, 40, size=plen)]
        gid = int(rng.integers(0, 3))
        budget = int(rng.integers(1, 9))
        jobs.append((prompt, gid, budget))

    # greedy truncation law: a budget-b run is the first b tokens of the
    # full-budget run (the budget only cuts the scan short), so one solo
    # generator + a slice serves every budget without extra compiles
    refs = [_solo_until_eos(gen, prompt, gid)[:budget] for prompt, gid, budget in jobs]
    batcher = ContinuousBatcher(gen, slots=3, decode_chunk=2, block_size=2, pool_blocks=9)
    try:
        streams = [
            batcher.submit(prompt, constraint=gid, max_new_tokens=budget)
            for prompt, gid, budget in jobs
        ]
        for i, (stream, ref) in enumerate(zip(streams, refs)):
            got = _collect(stream)
            assert got == ref, (i, jobs[i], got, ref)
        assert batcher.stats()["kv_blocks"]["used"] == 0  # allocator balanced
    finally:
        batcher.close()


def test_continuous_rejects_constraint_without_set(tiny):
    from unionml_tpu.serving import ContinuousBatcher

    module, params, _ = tiny
    gen = Generator(
        module, params,
        GenerationConfig(max_new_tokens=4, temperature=0.0, eos_id=EOS, prompt_buckets=(8,)),
    )
    batcher = ContinuousBatcher(gen, slots=1)
    try:
        with pytest.raises(ValueError, match="requires GenerationConfig.constraints"):
            batcher.submit([1, 2], constraint=1)
    finally:
        batcher.close()
