"""Multi-host fleet coordinator, in-process ring (docs/serving.md "Multi-host
fleets").

The cross-PROCESS contracts (real subprocess workers joining one
multi-process CPU JAX runtime) live in tests/emulated/test_cluster.py; this
ring pins the coordinator's routing/fleet logic cheaply with LocalHost
handles and a real WorkerAgent control server in the same process:

- **block-native payload**: a paged export ships block-aligned KV pages
  keyed by block position — never the ``cache_len``-wide dense row — and the
  npz wire round-trip preserves it exactly;
- **token identity**: streams routed through the coordinator (local AND
  remote hosts, plain and disaggregated) equal the sequential Generator
  oracle;
- **fleet-global prefix routing**: turn 2 of a conversation lands on the
  host whose radix tier already holds turn 1;
- **worker death**: a dead host is marked, routed around, and visible in the
  census — new work never sheds while a sibling lives;
- **cross-host elasticity**: ``scale_to`` distributes over live hosts and
  loses zero in-flight streams.
"""

import threading
import time

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from unionml_tpu.models import GenerationConfig, Generator, Llama, LlamaConfig
from unionml_tpu.serving import ContinuousBatcher, ReplicaSet
from unionml_tpu.serving.cluster import (
    FleetCoordinator,
    LocalHost,
    RemoteHost,
    WorkerAgent,
    _raise_shed,
    deserialize_handoff,
    serialize_handoff,
)
from unionml_tpu.serving.overload import (
    DeadlineExceeded,
    QueueFullError,
    TenantThrottled,
)


@pytest.fixture(scope="module")
def tiny():
    config = LlamaConfig.tiny(
        vocab_size=96, dim=64, n_layers=2, n_heads=4, n_kv_heads=2, hidden_dim=128,
        dtype=jnp.float32, param_dtype=jnp.float32,
    )
    module = Llama(config)
    params = module.init(jax.random.PRNGKey(1), jnp.zeros((1, 8), jnp.int32))["params"]
    return module, params


def _cfg(**overrides):
    kwargs = dict(max_new_tokens=8, temperature=0.0, prompt_buckets=(16,))
    kwargs.update(overrides)
    return GenerationConfig(**kwargs)


PROMPTS = [[3, 1, 4, 1, 5], [9, 2, 6, 5, 3, 5, 8, 9], [7, 1]]


def _drain(stream):
    return [int(t) for chunk in stream for t in np.asarray(chunk).ravel()]


def _expected(module, params, cfg, prompts):
    gen = Generator(module, params, cfg)
    return [list(map(int, gen([p])[0])) for p in prompts]


def _engine(tiny, cfg, **kwargs):
    module, params = tiny
    knobs = dict(slots=2, decode_chunk=4, block_size=8, pool_blocks=64)
    knobs.update(kwargs)
    return ContinuousBatcher(Generator(module, params, cfg), **knobs)


# ------------------------------------------------------------- block-native payload


def test_paged_export_ships_pages_not_dense_row(tiny):
    """The PR 9 follow-on, pinned: a paged engine's handoff payload is
    block-aligned pages (pool layout, exactly ceil(lengths/block) of them) —
    payload bytes scale with the prompt, not cache_len."""
    cfg = _cfg()
    engine = _engine(tiny, cfg, role="prefill")
    try:
        stream = engine.submit(PROMPTS[0], export_handoff=True)
        first = _drain(stream)
        payload = stream.handoff
        assert len(first) == 1
        assert payload is not None and "row" not in payload
        pages = payload["pages"]
        n_blocks = -(-payload["lengths"] // payload["block_size"])
        assert payload["block_size"] == 8
        for layer in pages:
            # pool layout: [H_kv, n_blocks, block_size, head_dim]
            assert layer["k"].shape[:3] == (2, n_blocks, 8)
    finally:
        engine.close(wait=False)


def test_handoff_wire_round_trip(tiny):
    cfg = _cfg()
    engine = _engine(tiny, cfg, role="prefill")
    try:
        stream = engine.submit(PROMPTS[1], export_handoff=True, deadline=time.monotonic() + 60)
        _drain(stream)
        payload = stream.handoff
        data = serialize_handoff(payload)
        back = deserialize_handoff(data)
        assert back["prompt"] == payload["prompt"]
        assert back["first"] == payload["first"]
        assert back["lengths"] == payload["lengths"]
        assert back["echo"] == payload["echo"]
        assert back["block_size"] == payload["block_size"]
        assert back["trace"] is None
        # the absolute-monotonic deadline is rebased, not shipped raw
        assert back["deadline"] == pytest.approx(payload["deadline"], abs=1.0)
        for sent, received in zip(payload["pages"], back["pages"]):
            for name in sent:
                np.testing.assert_array_equal(np.asarray(sent[name]), received[name])
    finally:
        engine.close(wait=False)


# -------------------------------------------------------------------- coordination


def test_coordinator_local_and_remote_hosts_token_identical(tiny):
    """A 2-host fleet (one direct handle, one behind a real control server)
    serves every stream token-identical to the sequential oracle, and the
    fleet surface (stats/health/census) reflects both hosts."""
    module, params = tiny
    cfg = _cfg()
    e0, e1 = _engine(tiny, cfg), _engine(tiny, cfg)
    agent = WorkerAgent(e1, process_id=1).start()
    coordinator = FleetCoordinator(
        [LocalHost(e0, host_id=0), RemoteHost(agent.address, host_id=1)]
    )
    try:
        got = [_drain(coordinator.submit(p)) for p in PROMPTS]
        assert got == _expected(module, params, cfg, PROMPTS)
        stats = coordinator.stats()
        assert stats["live_hosts"] == 2
        assert sum(coordinator._scheduler.stats()["submitted"]) == len(PROMPTS)
        assert [entry["alive"] for entry in stats["hosts"]] == [True, True]
        health = coordinator.health()
        assert health["state"] == "ok" and len(health["replicas"]) == 2
        census = coordinator.host_census()
        assert [entry["host"] for entry in census] == [0, 1]
        assert coordinator.occupancy() == (0, 0)
    finally:
        agent.close(close_engine=True)
        e0.close(wait=False)
        coordinator.close()


def test_cross_host_disaggregated_handoff_token_identical(tiny):
    """Host-level prefill/decode split over the control plane: the prompt
    prefills on the prefill host, its block-native payload crosses the wire,
    and the decode host's stream continues bit-identically."""
    module, params = tiny
    cfg = _cfg()
    prefill = _engine(tiny, cfg, role="prefill")
    decode = _engine(tiny, cfg, role="decode")
    agent = WorkerAgent(decode, process_id=1, role="decode").start()
    coordinator = FleetCoordinator(
        [LocalHost(prefill, host_id=0, role="prefill"),
         RemoteHost(agent.address, host_id=1, role="decode")],
        prefill_threshold=1,
    )
    try:
        got = [_drain(coordinator.submit(p)) for p in PROMPTS]
        assert got == _expected(module, params, cfg, PROMPTS)
        stats = coordinator.stats()
        assert stats["handoffs_cross_host"] == len(PROMPTS)
        assert stats["handoff_transfer_ms"]["window"] == len(PROMPTS)
        assert decode.handoffs_imported == len(PROMPTS)
        assert prefill.handoffs_exported == len(PROMPTS)
    finally:
        agent.close(close_engine=True)
        prefill.close(wait=False)


def test_fleet_global_prefix_routing_lands_on_warm_host(tiny):
    """The radix tier, fleet-global: host 1 serves turn 1; turn 2 (the whole
    prior exchange plus a new user turn) probes every host's actual cached
    length and lands on host 1 — even though pure load order favors host 0."""
    module, params = tiny
    cfg = _cfg()
    e0 = _engine(tiny, cfg, prefix_cache=True)
    e1 = _engine(tiny, cfg, prefix_cache=True)
    coordinator = FleetCoordinator([LocalHost(e0, host_id=0), LocalHost(e1, host_id=1)])
    try:
        turn1 = PROMPTS[1]
        reply = _drain(e1.submit(turn1))  # host 1 is the warm one, off-coordinator
        turn2 = list(turn1) + reply + [11, 12]
        # decode-side radix publish lands at slot release on the engine
        # thread, a beat after the last token reaches the consumer
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline and e1.cached_prefix_tokens(turn2) == 0:
            time.sleep(0.02)
        assert e1.cached_prefix_tokens(turn2) > 0 and e0.cached_prefix_tokens(turn2) == 0
        assert coordinator.cached_prefix_tokens(turn2) == e1.cached_prefix_tokens(turn2)
        warm = _drain(coordinator.submit(turn2))
        assert coordinator._scheduler.stats()["submitted"] == [0, 1]
        assert e1.prefix_cache_hits == 1
        # warm output equals a cold run of the same prompt (bit-identity
        # through the cache, one fleet level up)
        cold = _expected(module, params, cfg, [turn2])[0]
        assert warm == cold
    finally:
        e0.close(wait=False)
        e1.close(wait=False)


def test_worker_death_routes_around_and_census_reflects_it(tiny):
    module, params = tiny
    cfg = _cfg()
    e0, e1 = _engine(tiny, cfg), _engine(tiny, cfg)
    agent = WorkerAgent(e1, process_id=1).start()
    coordinator = FleetCoordinator(
        [LocalHost(e0, host_id=0), RemoteHost(agent.address, host_id=1)]
    )
    try:
        assert _drain(coordinator.submit(PROMPTS[0])) == _expected(module, params, cfg, PROMPTS[:1])[0]
        agent.close(close_engine=True)  # the worker dies
        # every subsequent submission sheds nothing: the probe failure marks
        # host 1 dead and the walk lands on host 0
        got = [_drain(coordinator.submit(p)) for p in PROMPTS]
        assert got == _expected(module, params, cfg, PROMPTS)
        assert coordinator.hosts[1].alive is False
        assert coordinator.host_failures >= 1
        stats = coordinator.stats()
        assert stats["live_hosts"] == 1
        census = coordinator.host_census()
        assert census[1]["alive"] is False and census[1]["replicas"] == 0
        assert coordinator.health()["state"] == "breach"  # a dead host pages
    finally:
        e0.close(wait=False)


def test_all_hosts_dead_raises(tiny):
    cfg = _cfg()
    e1 = _engine(tiny, cfg)
    agent = WorkerAgent(e1, process_id=0).start()
    coordinator = FleetCoordinator([RemoteHost(agent.address, host_id=0)])
    agent.close(close_engine=True)
    with pytest.raises(RuntimeError, match="dead"):
        coordinator.submit(PROMPTS[0])


def test_scale_to_distributes_over_hosts_with_zero_stream_loss(tiny):
    """Cross-host elasticity: the coordinator spreads the fleet total over
    live hosts; streams in flight through both resizes complete exactly."""
    module, params = tiny
    cfg = _cfg(max_new_tokens=16)
    rs0 = ReplicaSet.build(module, params, cfg, replicas=1,
                           slots=2, decode_chunk=2, block_size=8, pool_blocks=64)
    rs1 = ReplicaSet.build(module, params, cfg, replicas=1,
                           slots=2, decode_chunk=2, block_size=8, pool_blocks=64)
    coordinator = FleetCoordinator([LocalHost(rs0, host_id=0), LocalHost(rs1, host_id=1)])
    results: "dict[int, list]" = {}

    def consume(index, stream):
        out = []
        for chunk in stream:
            out.extend(int(t) for t in np.asarray(chunk).ravel())
            time.sleep(0.01)  # keep the stream alive across the resizes
        results[index] = out

    try:
        streams = [coordinator.submit(p) for p in PROMPTS]
        threads = [
            threading.Thread(target=consume, args=(i, s)) for i, s in enumerate(streams)
        ]
        for thread in threads:
            thread.start()
        assert coordinator.scale_to(4) == 4  # 2 per host, warmed before joining
        assert rs0.replicas == 2 and rs1.replicas == 2
        assert coordinator.scale_to(2) == 2  # tails drain with zero loss
        assert rs0.replicas == 1 and rs1.replicas == 1
        for thread in threads:
            thread.join(timeout=120)
        expected = _expected(module, params, cfg, PROMPTS)
        assert [results[i] for i in range(len(PROMPTS))] == expected
        with pytest.raises(ValueError):
            coordinator.scale_to(1)  # below one replica per live host
    finally:
        coordinator.close()


# ------------------------------------------------------------------ shed semantics


def test_shed_mapping_preserves_types_and_retry_after():
    with pytest.raises(TenantThrottled) as excinfo:
        _raise_shed(429, {"kind": "tenant_limit", "detail": "t", "retry_after": 2.5, "tenant": "acme"})
    assert excinfo.value.retry_after_s == 2.5 and excinfo.value.tenant == "acme"
    with pytest.raises(QueueFullError) as excinfo:
        _raise_shed(429, {"kind": "queue_full", "detail": "q", "retry_after": 1.5})
    assert excinfo.value.retry_after_s == 1.5
    with pytest.raises(DeadlineExceeded):
        _raise_shed(503, {"kind": "deadline", "detail": "late"})
    with pytest.raises(RuntimeError):
        _raise_shed(500, {"detail": "boom"})


def test_expired_deadline_sheds_before_routing(tiny):
    cfg = _cfg()
    engine = _engine(tiny, cfg)
    coordinator = FleetCoordinator([LocalHost(engine, host_id=0)])
    try:
        with pytest.raises(DeadlineExceeded):
            coordinator.submit(PROMPTS[0], deadline=time.monotonic() - 1.0)
        assert coordinator.shed_deadline == 1
    finally:
        engine.close(wait=False)


def test_host_roles_validation(tiny):
    cfg = _cfg()
    engine = _engine(tiny, cfg)
    try:
        with pytest.raises(ValueError):
            FleetCoordinator([LocalHost(engine)], host_roles=["prefill", "decode"])
        with pytest.raises(ValueError):
            FleetCoordinator([])
        coordinator = FleetCoordinator(
            [LocalHost(engine, host_id=0)], host_roles=["decode"]
        )
        assert coordinator.roles == ["decode"]
    finally:
        engine.close(wait=False)
