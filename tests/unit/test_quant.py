"""Weight-only int8 quantization correctness."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from unionml_tpu.models import GenerationConfig, Generator, Llama, LlamaConfig
from unionml_tpu.ops.quant import QuantizedTensor, dequantize, dequantize_tree, quantize_array, quantize_params


def _flat_by_path(tree):
    """{'a/b/c': leaf} view of a (possibly quantized) params tree."""
    return {
        "/".join(str(getattr(p, "key", p)) for p in path): leaf
        for path, leaf in jax.tree_util.tree_flatten_with_path(
            tree, is_leaf=lambda x: isinstance(x, QuantizedTensor)
        )[0]
    }


def test_roundtrip_error_bound():
    rng = np.random.default_rng(0)
    w = rng.normal(size=(256, 512)).astype(np.float32) * rng.uniform(0.01, 10, size=(1, 512))
    qt = quantize_array(w)
    assert qt.q.dtype == jnp.int8 and qt.q.shape == w.shape
    back = np.asarray(dequantize(qt, jnp.float32))
    # symmetric per-channel int8: error per element <= scale/2 = abs_max/254
    col_max = np.abs(w).max(axis=0)
    assert (np.abs(back - w) <= col_max / 254 + 1e-6).all()


def test_quantize_params_selects_matmul_kernels_only():
    config = LlamaConfig.tiny(dtype=jnp.float32, param_dtype=jnp.float32)
    module = Llama(config)
    params = module.init(jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))["params"]
    qparams = quantize_params(params, min_size=1)

    flat = _flat_by_path(qparams)
    assert isinstance(flat["layer_0/attn/q_proj/kernel"], QuantizedTensor)
    assert isinstance(flat["layer_0/mlp/wi/kernel"], QuantizedTensor)
    assert isinstance(flat["lm_head/kernel"], QuantizedTensor)
    assert not isinstance(flat["embed/embedding"], QuantizedTensor)  # gathers, not matmuls
    assert not isinstance(flat["final_norm/scale"], QuantizedTensor)


def test_quantized_forward_stays_close():
    config = LlamaConfig.tiny(dtype=jnp.float32, param_dtype=jnp.float32)
    module = Llama(config)
    params = module.init(jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))["params"]
    tokens = jnp.asarray([[3, 1, 4, 1, 5, 9, 2, 6]], jnp.int32)

    ref = module.apply({"params": params}, tokens)
    deq = dequantize_tree(quantize_params(params, min_size=1), dtype=jnp.float32)
    out = module.apply({"params": deq}, tokens)
    # logits drift stays small relative to the logits' own scale
    denom = float(jnp.abs(ref).max())
    assert float(jnp.abs(out - ref).max()) / denom < 0.05


def test_quantized_generation_runs_and_is_deterministic():
    config = LlamaConfig.tiny(dtype=jnp.float32, param_dtype=jnp.float32)
    module = Llama(config)
    params = module.init(jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))["params"]
    gen = Generator(
        module, params,
        GenerationConfig(max_new_tokens=8, temperature=0.0, prompt_buckets=(16,)),
        quantize="int8",
    )
    prompts = [[5, 6, 7], [1, 2, 3, 4, 5, 6]]
    out = gen(prompts)
    assert out.shape == (2, 8)
    np.testing.assert_array_equal(out, gen(prompts))


def test_int8_matmul_kernel_matches_dequant_reference():
    """Pallas kernel (interpret mode on CPU) vs dequant + dot, several shapes
    incl. M needing padding and the fallback path for untileable shapes."""
    from unionml_tpu.ops.int8_matmul import int8_matmul, quantized_matmul

    rng = np.random.default_rng(1)
    for m, k, f in [(8, 256, 512), (5, 512, 1536), (130, 128, 256)]:
        qt = quantize_array(rng.normal(size=(k, f)).astype(np.float32))
        x = jnp.asarray(rng.normal(size=(m, k)), jnp.float32)
        ref = np.asarray(x) @ (np.asarray(qt.q, np.float32) * np.asarray(qt.scale))
        out = np.asarray(int8_matmul(x, qt.q, qt.scale, out_dtype=jnp.float32, interpret=True))
        scale_ref = np.abs(ref).max() + 1e-9
        assert np.abs(out - ref).max() / scale_ref < 0.01  # bf16 x-cast rounding

    # untileable weight shape: quantized_matmul silently takes the dequant path
    qt = quantize_array(rng.normal(size=(96, 100)).astype(np.float32))
    x = jnp.asarray(rng.normal(size=(4, 96)), jnp.float32)
    out = quantized_matmul(x, qt, out_dtype=jnp.float32, impl="pallas")
    ref = np.asarray(x) @ (np.asarray(qt.q, np.float32) * np.asarray(qt.scale))
    np.testing.assert_allclose(np.asarray(out), ref, rtol=1e-5, atol=1e-5)

    # batched leading dims flow through
    x3 = jnp.asarray(rng.normal(size=(2, 3, 96)), jnp.float32)
    out3 = quantized_matmul(x3, qt, out_dtype=jnp.float32)
    assert out3.shape == (2, 3, 100)


def test_stacked_expert_kernels_get_per_expert_scales():
    """[E, K, F] expert stacks reduce only the contraction axis: per-(expert,
    channel) scales, so one outlier expert cannot crush the others' resolution."""
    rng = np.random.default_rng(3)
    w = rng.normal(size=(4, 32, 16)).astype(np.float32)
    w[2] *= 100.0  # outlier expert
    qt = quantize_array(w)
    assert qt.scale.shape == (4, 1, 16)
    back = np.asarray(dequantize(qt, jnp.float32))
    # per-expert error bound: each expert's channels quantize against its own max
    for e in range(4):
        col_max = np.abs(w[e]).max(axis=0)
        assert (np.abs(back[e] - w[e]) <= col_max / 254 + 1e-6).all(), e


def test_moe_int8_generation_runs_and_router_stays_fp():
    """MoE int8: stacked [E, K, F] expert kernels quantize (sized above the
    Generator's default min_size so generation really runs the int8 path) and
    dequant in-jit; the (precision-sensitive, f32-by-design) router never does."""
    from unionml_tpu.models import MoEConfig, MoETransformer

    # experts wi: [4, 128, 128] = 65536 elements >= Generator's min_size
    config = MoEConfig.tiny(
        vocab_size=61, dim=128, n_heads=4, n_kv_heads=2, hidden_dim=128,
        n_experts=4, k=2, capacity_factor=8.0, dtype=jnp.float32, param_dtype=jnp.float32,
    )
    module = MoETransformer(config)
    params = module.init(jax.random.PRNGKey(2), jnp.zeros((1, 8), jnp.int32))["params"]

    flat = _flat_by_path(quantize_params(params))  # Generator's own defaults
    assert isinstance(flat["layer_0/moe/experts/wi/kernel"], QuantizedTensor)
    assert flat["layer_0/moe/experts/wi/kernel"].scale.shape == (4, 1, 128)
    assert not isinstance(flat["layer_0/moe/router/kernel"], QuantizedTensor)

    gen = Generator(
        module, params,
        GenerationConfig(max_new_tokens=6, temperature=0.0, prompt_buckets=(16,)),
        quantize="int8",
    )
    assert any(
        isinstance(leaf, QuantizedTensor)
        for leaf in jax.tree_util.tree_leaves(gen.params, is_leaf=lambda x: isinstance(x, QuantizedTensor))
    )
    out = gen([[3, 1, 4], [1, 5, 9, 2]])
    assert out.shape == (2, 6)
    np.testing.assert_array_equal(out, gen([[3, 1, 4], [1, 5, 9, 2]]))


def test_int8_kv_cache_logits_stay_close():
    """Prefill through an int8 KV cache must reproduce the fp-cache logits to
    per-(position, head) int8 quantization error (~1%)."""
    from unionml_tpu.models import init_cache

    config = LlamaConfig.tiny(
        vocab_size=64, dim=64, n_layers=2, n_heads=4, n_kv_heads=2, hidden_dim=128,
        dtype=jnp.float32, param_dtype=jnp.float32,
    )
    module = Llama(config)
    params = module.init(jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))["params"]
    tokens = jnp.asarray([[3, 1, 4, 1, 5, 9, 2, 6]], jnp.int32)
    positions = jnp.broadcast_to(jnp.arange(8)[None], (1, 8))

    ref, _ = module.apply(
        {"params": params}, tokens, positions=positions, cache=init_cache(config, 1, 16)
    )
    out, qcache = module.apply(
        {"params": params}, tokens, positions=positions, cache=init_cache(config, 1, 16, kv_dtype="int8")
    )
    assert qcache[0]["k"].dtype == jnp.int8 and qcache[0]["k_scale"].shape == (1, 16, 2, 1)
    denom = float(jnp.abs(ref).max())
    assert float(jnp.abs(out - ref).max()) / denom < 0.02


def test_int8_kv_cache_generation_runs_and_composes_with_int8_weights():
    config = LlamaConfig.tiny(
        vocab_size=64, dim=64, n_layers=2, n_heads=4, n_kv_heads=2, hidden_dim=128,
        dtype=jnp.float32, param_dtype=jnp.float32,
    )
    module = Llama(config)
    params = module.init(jax.random.PRNGKey(1), jnp.zeros((1, 8), jnp.int32))["params"]
    gen = Generator(
        module, params,
        GenerationConfig(max_new_tokens=8, temperature=0.0, prompt_buckets=(16,), kv_cache_dtype="int8"),
        quantize="int8",
    )
    prompts = [[5, 6, 7], [1, 2, 3, 4]]
    out = gen(prompts)
    assert out.shape == (2, 8)
    np.testing.assert_array_equal(out, gen(prompts))
    # streaming path shares the cache machinery
    chunks = list(gen.stream(prompts, chunk_size=3))
    assert np.concatenate(chunks, axis=1).shape[1] <= 8


def test_quantize_params_min_size_and_path_filters():
    """The selection edges serving depends on: ``min_size`` keeps small
    kernels full precision (a tiny model quantizes NOTHING under the default
    threshold — no silent accuracy tax for no bandwidth win), and the
    include/exclude regexes retarget selection without touching the tree
    walk."""
    config = LlamaConfig.tiny(
        vocab_size=61, dim=64, n_layers=1, n_heads=4, n_kv_heads=2, hidden_dim=128,
        dtype=jnp.float32, param_dtype=jnp.float32,
    )
    module = Llama(config)
    params = module.init(jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))["params"]

    # default min_size (1 << 16): every kernel of this tiny config is smaller,
    # so the tree passes through untouched
    untouched = _flat_by_path(quantize_params(params))
    assert not any(isinstance(leaf, QuantizedTensor) for leaf in untouched.values())
    # threshold boundary: exactly min_size elements quantizes (>=, not >)
    wi = _flat_by_path(params)["layer_0/mlp/wi/kernel"]
    boundary = int(np.prod(wi.shape))
    flat = _flat_by_path(quantize_params(params, min_size=boundary))
    assert isinstance(flat["layer_0/mlp/wi/kernel"], QuantizedTensor)

    # include narrows to one projection; everything else stays fp
    flat = _flat_by_path(quantize_params(params, include=r"q_proj/kernel$", min_size=1))
    assert isinstance(flat["layer_0/attn/q_proj/kernel"], QuantizedTensor)
    assert not isinstance(flat["layer_0/attn/k_proj/kernel"], QuantizedTensor)
    assert not isinstance(flat["lm_head/kernel"], QuantizedTensor)

    # exclude carves the head out of the default include
    flat = _flat_by_path(quantize_params(params, exclude=r"(embed|norm|lm_head)", min_size=1))
    assert not isinstance(flat["lm_head/kernel"], QuantizedTensor)
    assert isinstance(flat["layer_0/attn/q_proj/kernel"], QuantizedTensor)


def test_quantized_shardings_strip_axes_on_unit_dims():
    """_quantized_shardings: the int8 values keep the kernel's resolved
    sharding while the per-channel scale keeps mesh axes ONLY on its non-unit
    dims — a size-1 reduction dim carrying a mesh axis would be an invalid
    sharding. Covers the 2D kernel and the stacked [E, K, F] expert case
    (whose scale is [E, 1, F]: the middle axis must strip, the outer ones
    survive)."""
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from unionml_tpu.models.generate import _quantized_shardings

    mesh = Mesh(np.asarray(jax.devices()[:1]).reshape(1, 1), ("data", "model"))
    rng = np.random.default_rng(5)
    qparams = {
        "dense": quantize_array(rng.normal(size=(32, 16)).astype(np.float32)),
        "experts": quantize_array(rng.normal(size=(4, 32, 16)).astype(np.float32)),
        "plain": jnp.zeros((8, 8), jnp.float32),
    }
    shardings = {
        "dense": NamedSharding(mesh, P("data", "model")),
        "experts": NamedSharding(mesh, P("data", None, "model")),
        "plain": NamedSharding(mesh, P(None, "model")),
    }
    fixed = _quantized_shardings(qparams, shardings, mesh)
    # dense kernel [32, 16] -> scale [1, 16]: the size-1 dim drops its axis
    assert fixed["dense"].q.spec == P("data", "model")
    assert fixed["dense"].scale.spec == P(None, "model")
    # expert stack [4, 32, 16] -> scale [4, 1, 16]: only the unit dim strips
    assert fixed["experts"].q.spec == P("data", None, "model")
    assert fixed["experts"].scale.spec == P("data", None, "model")
    # non-quantized leaves pass their sharding through untouched
    assert fixed["plain"].spec == P(None, "model")


def test_unsupported_mode_rejected():
    config = LlamaConfig.tiny(dtype=jnp.float32, param_dtype=jnp.float32)
    module = Llama(config)
    params = module.init(jax.random.PRNGKey(1), jnp.zeros((1, 8), jnp.int32))["params"]
    with pytest.raises(ValueError, match="int8"):
        Generator(module, params, GenerationConfig(), quantize="fp4")
