"""AOT program store: load-before-compile serving cold starts.

The contract under test (docs/serving.md "Cold start and AOT preload"): a
process whose store holds this topology's programs warms with ZERO fresh XLA
traces and serves tokens bit-identical to a freshly-compiled engine; stale
entries (other jax version, other mesh) and corrupted entries are *skipped* —
the engine compiles exactly as it would without the store, never crashes.
"""

import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from unionml_tpu.models import GenerationConfig, Generator, Llama, LlamaConfig
from unionml_tpu.serving import ContinuousBatcher
from unionml_tpu.serving.aot import ProgramStore, resolve_store

PROMPT = [3, 14, 15, 9, 2]


@pytest.fixture(scope="module")
def tiny():
    config = LlamaConfig.tiny(
        vocab_size=89, dim=32, n_layers=2, n_heads=2, n_kv_heads=2, hidden_dim=64,
        dtype=jnp.float32, param_dtype=jnp.float32,
    )
    module = Llama(config)
    params = module.init(jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))["params"]
    return module, params


def _cfg():
    return GenerationConfig(max_new_tokens=6, temperature=0.0, prompt_buckets=(8, 16))


def _drain(stream):
    return [int(t) for chunk in stream for t in np.asarray(chunk).ravel()]


def _serve_one(module, params, tmp, **engine_kwargs):
    gen = Generator(module, params, _cfg())
    batcher = ContinuousBatcher(gen, slots=2, decode_chunk=4, aot=tmp, **engine_kwargs)
    try:
        batcher.warmup()
        tokens = _drain(batcher.submit(PROMPT))
        stats = batcher.stats()
        return gen, tokens, stats
    finally:
        batcher.close()


# ------------------------------------------------------------------ key derivation


def test_entry_key_stable_and_sensitive(tmp_path):
    store = ProgramStore(str(tmp_path))
    key = store.entry_key("prefill", {"mesh": None}, ("sig",))
    assert key == store.entry_key("prefill", {"mesh": None}, ("sig",))  # deterministic
    assert key != store.entry_key("decode", {"mesh": None}, ("sig",))  # program name
    assert key != store.entry_key("prefill", {"mesh": [0, 1]}, ("sig",))  # context
    assert key != store.entry_key("prefill", {"mesh": None}, ("other",))  # signature
    # the store-level context (jax version, backend, device ids) keys too
    other = ProgramStore(str(tmp_path))
    other._context = dict(other._context, jax="0.0.0-stale")
    assert key != other.entry_key("prefill", {"mesh": None}, ("sig",))


def test_store_meta_sidecars_record_programs(tmp_path, tiny):
    module, params = tiny
    _serve_one(module, params, str(tmp_path))
    entries = ProgramStore(str(tmp_path)).entries()
    assert entries, "warmup should have persisted entries"
    programs = {entry["program"] for entry in entries}
    assert "prefill" in programs and "decode" in programs
    for entry in entries:
        assert entry["store"]["jax"] == jax.__version__
        assert "signature" in entry and "context" in entry


# ------------------------------------------------------------------ exactness


def test_populated_store_serves_with_zero_traces_and_identical_tokens(tmp_path, tiny):
    module, params = tiny
    # reference: a plain-jit engine (no store anywhere near it)
    ref_gen = Generator(module, params, _cfg())
    ref_b = ContinuousBatcher(ref_gen, slots=2, decode_chunk=4)
    try:
        ref_b.warmup()
        ref = _drain(ref_b.submit(PROMPT))
    finally:
        ref_b.close()

    gen1, out1, stats1 = _serve_one(module, params, str(tmp_path))
    assert out1 == ref  # serialize-on-compile must not perturb the program
    assert stats1["aot"]["programs_compiled"] > 0
    assert stats1["aot"]["programs_serialized"] == stats1["aot"]["programs_compiled"]
    assert stats1["aot"]["programs_loaded"] == 0

    gen2, out2, stats2 = _serve_one(module, params, str(tmp_path))
    assert out2 == ref  # the pinned contract: AOT-loaded == freshly-compiled
    assert out2[0] == ref[0]  # first sampled token bit-identical, explicitly
    assert (gen2.prefill_traces, gen2.decode_traces) == (0, 0)  # zero fresh XLA traces
    assert stats2["aot"]["programs_compiled"] == 0
    assert stats2["aot"]["programs_loaded"] > 0
    assert stats2["aot"]["load_ms"]["window"] == stats2["aot"]["programs_loaded"]
    assert stats2["aot"]["compile_ms"] == {"window": 0}  # never a None gauge


def test_generator_warmup_preloads(tmp_path, tiny):
    module, params = tiny
    ref = Generator(module, params, _cfg())([PROMPT])
    store = ProgramStore(str(tmp_path))
    Generator(module, params, _cfg()).enable_aot(store).warmup()
    assert store.programs_compiled > 0

    store2 = ProgramStore(str(tmp_path))
    gen2 = Generator(module, params, _cfg()).enable_aot(store2).warmup()
    assert store2.programs_compiled == 0 and store2.programs_loaded > 0
    assert (gen2.prefill_traces, gen2.decode_traces) == (0, 0)
    np.testing.assert_array_equal(gen2([PROMPT]), ref)
    assert (gen2.prefill_traces, gen2.decode_traces) == (0, 0)  # the call itself hit too


# ------------------------------------------------------------------ staleness / corruption


def test_stale_jax_version_entries_are_skipped(tmp_path, tiny):
    module, params = tiny
    stale = ProgramStore(str(tmp_path))
    stale._context = dict(stale._context, jax="0.0.0-stale")
    Generator(module, params, _cfg()).enable_aot(stale).warmup()
    n_entries = stale.entry_count()
    assert n_entries > 0

    # a correctly-versioned store over the same dir must not load any of them
    fresh = ProgramStore(str(tmp_path))
    gen = Generator(module, params, _cfg()).enable_aot(fresh).warmup()
    assert fresh.programs_loaded == 0  # stale keys never resolve
    assert fresh.programs_compiled > 0  # ...so it compiled, without crashing
    assert gen.prefill_traces > 0
    assert fresh.entry_count() == n_entries * 2  # old entries orphaned, not clobbered


def test_mesh_mismatch_entries_are_skipped(tmp_path, tiny):
    if len(jax.devices()) < 2:
        pytest.skip("needs 2 emulated devices")
    from jax.sharding import Mesh

    from unionml_tpu.parallel.mesh import AXIS_ORDER

    module, params = tiny
    shape = (1,) * len(AXIS_ORDER)

    def one_device_mesh(i):
        return Mesh(np.asarray([jax.devices()[i]]).reshape(shape), AXIS_ORDER)

    s0 = ProgramStore(str(tmp_path))
    Generator(module, params, _cfg(), mesh=one_device_mesh(0)).enable_aot(s0).warmup()
    assert s0.programs_compiled > 0

    # same program shapes, DIFFERENT device assignment: must miss, not load
    s1 = ProgramStore(str(tmp_path))
    Generator(module, params, _cfg(), mesh=one_device_mesh(1)).enable_aot(s1).warmup()
    assert s1.programs_loaded == 0
    assert s1.programs_compiled > 0


def test_corrupted_entries_fall_back_to_compile(tmp_path, tiny):
    module, params = tiny
    _, ref, _ = _serve_one(module, params, str(tmp_path))
    for name in os.listdir(tmp_path):
        if name.endswith(".aotx"):
            (tmp_path / name).write_bytes(b"not a pickled executable")

    gen, out, stats = _serve_one(module, params, str(tmp_path))
    assert out == ref  # corruption degrades to compile, identically
    assert stats["aot"]["load_failures"] > 0
    assert stats["aot"]["programs_compiled"] > 0
    assert gen.prefill_traces > 0

    # the recompile overwrote the corrupt entries: a third engine loads clean
    gen3, out3, stats3 = _serve_one(module, params, str(tmp_path))
    assert out3 == ref
    assert stats3["aot"]["load_failures"] == 0
    assert stats3["aot"]["programs_loaded"] > 0
    assert (gen3.prefill_traces, gen3.decode_traces) == (0, 0)


# ------------------------------------------------------------------ knobs / degrade


def test_unusable_store_dir_degrades_to_plain_jit(tmp_path, tiny):
    module, params = tiny
    blocker = tmp_path / "not_a_dir"
    blocker.write_text("occupied")
    assert resolve_store(str(blocker / "sub")) is None  # warned + disabled
    gen, out, stats = _serve_one(module, params, str(blocker / "sub"))
    assert "aot" not in stats  # byte-for-byte the plain engine's stats
    assert len(out) == _cfg().max_new_tokens


def test_env_resolution(tmp_path, monkeypatch, tiny):
    from unionml_tpu.defaults import serve_aot_preload

    monkeypatch.delenv("UNIONML_TPU_AOT_PRELOAD", raising=False)
    assert serve_aot_preload() is None
    assert resolve_store(None) is None
    monkeypatch.setenv("UNIONML_TPU_AOT_PRELOAD", "0")
    assert serve_aot_preload() is None
    monkeypatch.setenv("UNIONML_TPU_AOT_PRELOAD", "1")
    assert serve_aot_preload() == "~/.cache/unionml_tpu/aot"
    monkeypatch.setenv("UNIONML_TPU_AOT_PRELOAD", str(tmp_path))
    assert serve_aot_preload() == str(tmp_path)

    # an engine built with aot=None (the default) reads the export
    module, params = tiny
    batcher = ContinuousBatcher(Generator(module, params, _cfg()), slots=1, decode_chunk=4)
    try:
        assert batcher._aot is not None
        assert batcher._aot.root == str(tmp_path)
    finally:
        batcher.close()


def test_aot_off_keeps_stats_byte_for_byte(tiny):
    module, params = tiny
    batcher = ContinuousBatcher(Generator(module, params, _cfg()), slots=1, decode_chunk=4)
    try:
        assert "aot" not in batcher.stats()
    finally:
        batcher.close()


def test_aot_stats_render_clean_prometheus(tmp_path):
    """The /metrics no-None-gauge contract: the aot section (counters +
    latency windows, populated or empty) renders as clean exposition."""
    from unionml_tpu.observability.prometheus import render

    store = ProgramStore(str(tmp_path))
    store.note_compiled(0.5)
    store.note_loaded(0.01)
    text = render({"generation": {"aot": store.stats()}})
    assert "unionml_tpu_generation_aot_programs_loaded 1" in text
    assert 'unionml_tpu_generation_aot_load{quantile="0.99"}' in text
    assert "None" not in text
    empty = render({"generation": {"aot": ProgramStore(str(tmp_path)).stats()}})
    assert "unionml_tpu_generation_aot_programs_loaded 0" in empty
    assert "None" not in empty


# ------------------------------------------------------------------ serverless


def test_serverless_scale_to_zero_takes_the_preload_path(tmp_path, tiny):
    """The acceptance pin: a scaled-from-zero container's ONE startup restores
    the generator's executables from the store — zero fresh XLA traces — and
    later invocations reuse the warmed engine without re-running startup."""
    from unionml_tpu.serving.serverless import lambda_handler

    module, params = tiny
    _serve_one(module, params, str(tmp_path))  # a previous process populated the store

    class _Server:
        async def dispatch_with_headers(self, method, path, body, headers):
            return 200, {"ok": True}, "application/json", {}

    class _Serving:
        def __init__(self):
            self._started = False
            self.server = _Server()
            self.batcher = None

        def startup(self):
            if self._started:
                return
            gen = Generator(module, params, _cfg())
            self.batcher = ContinuousBatcher(gen, slots=2, decode_chunk=4, aot=str(tmp_path))
            self.batcher.warmup()
            self._started = True

    serving = _Serving()
    handler = lambda_handler(serving)
    event = {"httpMethod": "GET", "path": "/health"}
    try:
        assert handler(event, None)["statusCode"] == 200
        gen = serving.batcher.gen
        assert (gen.prefill_traces, gen.decode_traces) == (0, 0)  # restored, not compiled
        aot = serving.batcher.stats()["aot"]
        assert aot["programs_compiled"] == 0 and aot["programs_loaded"] > 0
        assert handler(event, None)["statusCode"] == 200
        assert handler.stats == {
            "invocations": 2, "startups": 1,
            "cold_start_s": handler.stats["cold_start_s"],
        }
        assert serving.batcher.gen is gen  # the warmed engine was reused, not rebuilt
    finally:
        if serving.batcher is not None:
            serving.batcher.close()


# ------------------------------------------------------------------ elastic scale-up


def test_meshless_scale_up_reuses_store_on_revisited_device(tmp_path, tiny):
    """scale down → scale up re-places the replica on the same device; with the
    store warm the rejoining engine must not produce a single fresh trace."""
    if len(jax.devices()) < 2:
        pytest.skip("needs 2 emulated devices")
    from unionml_tpu.serving import ReplicaSet

    module, params = tiny
    ref = Generator(module, params, _cfg())([PROMPT])[0]
    rs = ReplicaSet.build(
        module, params, _cfg(), mesh=None, replicas=2,
        slots=2, decode_chunk=4, aot=str(tmp_path),
    )
    try:
        rs.warmup()
        assert rs.scale_to(1) == 1
        assert rs.scale_to(2) == 2  # rejoins on the round-robin device it left
        new_engine = rs.batchers[1]
        assert (new_engine.gen.prefill_traces, new_engine.gen.decode_traces) == (0, 0)
        aot = new_engine.stats()["aot"]
        assert aot["programs_compiled"] == 0 and aot["programs_loaded"] > 0
        assert _drain(new_engine.submit(PROMPT)) == list(ref)
        assert (new_engine.gen.prefill_traces, new_engine.gen.decode_traces) == (0, 0)
        fleet = rs.stats()
        assert fleet["aot"]["programs_loaded"] > 0  # fleet-wide aggregation
    finally:
        rs.close()
