"""The shared jax.distributed bootstrap (unionml_tpu/distributed.py): env
readers follow the defaults.py warn-and-degrade contract, the single-process
degenerate forms of every collective are exact no-ops, and job_runner
consumes the extracted bootstrap (one code path for train AND serve)."""

import pytest

from unionml_tpu import distributed
from unionml_tpu.defaults import (
    distributed_coordinator,
    distributed_num_processes,
    distributed_process_id,
    fleet_dir,
    fleet_host_roles,
)


def test_env_readers_defaults(monkeypatch):
    for name in (
        "UNIONML_TPU_COORDINATOR", "UNIONML_TPU_NUM_PROCESSES", "UNIONML_TPU_PROCESS_ID",
        "UNIONML_TPU_FLEET_DIR", "UNIONML_TPU_HOST_ROLES",
    ):
        monkeypatch.delenv(name, raising=False)
    assert distributed_coordinator() is None
    assert distributed_num_processes() == 1
    assert distributed_process_id() == 0
    assert fleet_dir() == ".unionml_fleet"
    assert fleet_host_roles() == {}


def test_env_readers_parse_and_degrade(monkeypatch, caplog):
    from unionml_tpu._logging import logger

    monkeypatch.setattr(logger, "propagate", True)
    monkeypatch.setenv("UNIONML_TPU_COORDINATOR", " 10.0.0.1:1234 ")
    monkeypatch.setenv("UNIONML_TPU_NUM_PROCESSES", "4")
    monkeypatch.setenv("UNIONML_TPU_PROCESS_ID", "3")
    monkeypatch.setenv("UNIONML_TPU_HOST_ROLES", "prefill=1,decode=3")
    assert distributed_coordinator() == "10.0.0.1:1234"
    assert distributed_num_processes() == 4
    assert distributed_process_id() == 3
    assert fleet_host_roles() == {"prefill": 1, "decode": 3}
    # garbage warns and degrades — a typo'd fleet env must never crash the
    # bootstrap (the env_int/env_choice contract, satellite-pinned)
    monkeypatch.setenv("UNIONML_TPU_NUM_PROCESSES", "many")
    monkeypatch.setenv("UNIONML_TPU_PROCESS_ID", "-2")
    monkeypatch.setenv("UNIONML_TPU_HOST_ROLES", "turbo=9")
    with caplog.at_level("WARNING", logger="unionml_tpu"):
        assert distributed_num_processes() == 1
        assert distributed_process_id() == 0
        assert fleet_host_roles() == {}
    assert any("many" in record.message for record in caplog.records)
    assert any("turbo=9" in record.message for record in caplog.records)


def test_single_process_collectives_are_no_ops(monkeypatch):
    for name in (
        "UNIONML_TPU_COORDINATOR", "UNIONML_TPU_NUM_PROCESSES", "UNIONML_TPU_PROCESS_ID",
    ):
        monkeypatch.delenv(name, raising=False)
    assert distributed.maybe_initialize() is False
    assert distributed.is_initialized() is False
    assert distributed.process_index() == 0
    assert distributed.process_count() == 1
    distributed.barrier("noop")  # must not touch jax at all
    config = {"builder": "app:build", "kwargs": {"slots": 2}}
    assert distributed.agree(config) == config
    assert distributed.allgather_ints(8123) == [8123]


def test_process_identity_tracks_env_before_init(monkeypatch):
    monkeypatch.setenv("UNIONML_TPU_NUM_PROCESSES", "2")
    monkeypatch.setenv("UNIONML_TPU_PROCESS_ID", "1")
    assert distributed.process_index() == 1
    assert distributed.process_count() == 2


def test_job_runner_delegates_to_shared_bootstrap(monkeypatch):
    from unionml_tpu import job_runner

    calls = []
    monkeypatch.setattr(distributed, "maybe_initialize", lambda: calls.append(1) or True)
    job_runner._maybe_init_distributed()
    assert calls == [1]
