"""Generation engine correctness.

Oracle: incremental (prefill + per-token decode through the KV cache) greedy
generation must produce exactly the tokens of a naive loop that re-runs the full
forward pass over the growing sequence each step — covering cache writes, RoPE
positions, GQA head mapping, and the visibility mask in one equivalence.
"""

from typing import List

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from unionml_tpu.models import GenerationConfig, Generator, Llama, LlamaConfig, init_cache, sample_tokens


@pytest.fixture(scope="module")
def tiny():
    config = LlamaConfig.tiny(
        vocab_size=97, dim=64, n_layers=2, n_heads=4, n_kv_heads=2, hidden_dim=128,
        dtype=jnp.float32, param_dtype=jnp.float32,
    )
    module = Llama(config)
    params = module.init(jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))["params"]
    return module, params, config


def naive_greedy(module, params, prompt: List[int], steps: int) -> List[int]:
    """Re-run the full (uncached) forward over the growing sequence each step."""
    tokens = list(prompt)
    for _ in range(steps):
        logits = module.apply({"params": params}, jnp.asarray([tokens], jnp.int32))
        tokens.append(int(jnp.argmax(logits[0, -1].astype(jnp.float32))))
    return tokens[len(prompt) :]


def test_greedy_matches_full_forward_oracle(tiny):
    module, params, _ = tiny
    gen = Generator(
        module, params, GenerationConfig(max_new_tokens=12, temperature=0.0, prompt_buckets=(16,))
    )
    prompt = [3, 14, 15, 92, 6, 5]
    out = gen([prompt])
    assert out.shape == (1, 12)
    assert out[0].tolist() == naive_greedy(module, params, prompt, 12)


def test_variable_length_batch_each_matches_its_own_oracle(tiny):
    module, params, _ = tiny
    gen = Generator(
        module, params, GenerationConfig(max_new_tokens=8, temperature=0.0, prompt_buckets=(16,))
    )
    prompts = [[7, 7, 7, 21, 40, 2, 19, 55, 31, 90], [1, 88], [44, 9, 62, 13, 5]]
    out = gen(prompts)
    assert out.shape == (3, 8)
    for row, prompt in zip(out, prompts):
        assert row.tolist() == naive_greedy(module, params, prompt, 8), prompt


def test_trace_counts_stay_bounded(tiny):
    module, params, _ = tiny
    gen = Generator(
        module, params, GenerationConfig(max_new_tokens=4, temperature=0.0, prompt_buckets=(8, 16))
    )
    gen([[1, 2, 3]])       # bucket 8, batch 1
    gen([[5, 8, 1, 2, 6]])  # bucket 8 again: no new trace
    gen([[4] * 12])        # bucket 16
    gen([[8] * 11])        # bucket 16 again: no new trace
    assert gen.prefill_traces == 2  # one per (bucket, batch) shape
    # cache_len is pinned to max(buckets) + max_new, so decode compiles exactly once
    assert gen.decode_traces == 1


def test_eos_pads_tail(tiny):
    module, params, _ = tiny
    base = Generator(module, params, GenerationConfig(max_new_tokens=6, temperature=0.0, prompt_buckets=(8,)))
    prompt = [10, 20, 30]
    free_run = base([prompt])[0].tolist()
    eos = free_run[1]
    cut = free_run.index(eos) + 1  # first occurrence ends the sequence
    gen = Generator(
        module, params,
        GenerationConfig(max_new_tokens=6, temperature=0.0, prompt_buckets=(8,), eos_id=eos, pad_id=0),
    )
    out = gen([prompt])[0].tolist()
    assert out[:cut] == free_run[:cut]  # up to and including the eos token
    assert out[cut:] == [0] * (6 - cut)


def test_sampling_top_k_one_is_greedy(tiny):
    module, params, _ = tiny
    greedy = Generator(
        module, params, GenerationConfig(max_new_tokens=6, temperature=0.0, prompt_buckets=(8,))
    )
    topk1 = Generator(
        module, params,
        GenerationConfig(max_new_tokens=6, temperature=0.7, top_k=1, prompt_buckets=(8,)),
    )
    prompt = [5, 6, 7, 8]
    assert greedy([prompt])[0].tolist() == topk1([prompt], seed=123)[0].tolist()


def test_sample_tokens_top_p_masks_tail():
    logits = jnp.log(jnp.asarray([[0.5, 0.3, 0.15, 0.05]]))
    cfg = GenerationConfig(temperature=1.0, top_p=0.6)
    # top_p=0.6 keeps {0.5, 0.3}; over many draws only tokens 0/1 may appear
    draws = {
        int(sample_tokens(logits, jax.random.PRNGKey(i), cfg)[0]) for i in range(50)
    }
    assert draws <= {0, 1} and 0 in draws


def test_sample_tokens_min_p_adapts_to_confidence():
    from unionml_tpu.models.generate import filtered_logits

    cfg = GenerationConfig(temperature=1.0, min_p=0.2)
    # confident head: 0.2 * 0.7 = 0.14 cuts the 0.1 and 0.05 tails
    sharp = jnp.log(jnp.asarray([[0.70, 0.15, 0.10, 0.05]]))
    kept = jnp.isfinite(filtered_logits(sharp, cfg))[0]
    assert kept.tolist() == [True, True, False, False]
    # flat distribution: 0.2 * 0.28 = 0.056 keeps everything — the filter is
    # permissive exactly when the model is unsure (unlike a fixed top_k)
    flat = jnp.log(jnp.asarray([[0.28, 0.26, 0.24, 0.22]]))
    assert bool(jnp.isfinite(filtered_logits(flat, cfg)).all())
    # composes with top_k: k=1 still wins after the min_p cut
    cfg2 = GenerationConfig(temperature=1.0, min_p=0.2, top_k=1)
    kept2 = jnp.isfinite(filtered_logits(sharp, cfg2))[0]
    assert kept2.tolist() == [True, False, False, False]


def test_stream_matches_call(tiny):
    module, params, _ = tiny
    gen = Generator(
        module, params, GenerationConfig(max_new_tokens=11, temperature=0.0, prompt_buckets=(16,))
    )
    prompts = [[3, 14, 15, 92], [7, 7]]
    full = gen(prompts)
    chunks = list(gen.stream(prompts, chunk_size=4))
    assert all(c.shape[1] <= 4 for c in chunks)
    np.testing.assert_array_equal(np.concatenate(chunks, axis=1), full)
    # sampled decoding streams identically too (same seed, same key path)
    sampled = Generator(
        module, params, GenerationConfig(max_new_tokens=9, temperature=0.9, prompt_buckets=(16,))
    )
    full_s = sampled(prompts, seed=5)
    chunks_s = list(sampled.stream(prompts, seed=5, chunk_size=3))
    np.testing.assert_array_equal(np.concatenate(chunks_s, axis=1), full_s)


def test_stream_stops_early_after_eos(tiny):
    module, params, _ = tiny
    base = Generator(module, params, GenerationConfig(max_new_tokens=10, temperature=0.0, prompt_buckets=(8,)))
    prompt = [10, 20, 30]
    free_run = base([prompt])[0].tolist()
    eos = free_run[1]
    gen = Generator(
        module, params,
        GenerationConfig(max_new_tokens=10, temperature=0.0, prompt_buckets=(8,), eos_id=eos, pad_id=0),
    )
    chunks = list(gen.stream([prompt], chunk_size=2))
    out = np.concatenate(chunks, axis=1)[0].tolist()
    cut = free_run.index(eos) + 1
    assert out[:cut] == free_run[:cut]
    assert all(t == 0 for t in out[cut:])
    # stream ended at a chunk boundary after every row finished, not at max_new
    assert len(out) < 10


def test_chunked_prefill_matches_single_dispatch(tiny):
    """Long-context prefill in fixed chunks through the cache must emit exactly
    the tokens of the one-dispatch prefill, across variable prompt lengths —
    and the chunk shape compiles once regardless of prompt length."""
    module, params, _ = tiny
    base_cfg = dict(max_new_tokens=6, temperature=0.0, prompt_buckets=(32,))
    plain = Generator(module, params, GenerationConfig(**base_cfg))
    chunked = Generator(module, params, GenerationConfig(**base_cfg, prefill_chunk=8))

    prompts = [[7, 7, 7, 21, 40, 2, 19, 55, 31, 90, 3, 14], [1, 88], list(range(1, 28))]
    np.testing.assert_array_equal(chunked(prompts), plain(prompts))
    np.testing.assert_array_equal(chunked([[5, 4, 3]]), plain([[5, 4, 3]]))

    sampled_cfg = dict(max_new_tokens=5, temperature=0.8, top_k=20, prompt_buckets=(32,))
    plain_s = Generator(module, params, GenerationConfig(**sampled_cfg))
    chunked_s = Generator(module, params, GenerationConfig(**sampled_cfg, prefill_chunk=8))
    np.testing.assert_array_equal(chunked_s(prompts, seed=3), plain_s(prompts, seed=3))


@pytest.mark.slow  # ~18s; MoE decode correctness stays covered in tier-1 by the
# padding-invariance test here and the expert-parallel equality ring in emulated/
def test_moe_greedy_matches_full_forward_oracle():
    """The MoE decoder follows the same cache contract; with ample expert capacity
    (no token drops) incremental routing equals whole-sequence routing, so greedy
    incremental decode must reproduce the naive full re-forward tokens."""
    from unionml_tpu.models import MoEConfig, MoETransformer

    config = MoEConfig.tiny(
        vocab_size=61, dim=64, n_layers=2, n_heads=4, n_kv_heads=2, hidden_dim=96,
        n_experts=4, k=2, capacity_factor=8.0, dtype=jnp.float32, param_dtype=jnp.float32,
    )
    module = MoETransformer(config)
    params = module.init(jax.random.PRNGKey(2), jnp.zeros((1, 8), jnp.int32))["params"]
    gen = Generator(
        module, params, GenerationConfig(max_new_tokens=8, temperature=0.0, prompt_buckets=(16,))
    )
    prompts = [[3, 1, 4, 1, 5], [9, 2]]
    out = gen(prompts)
    for row, prompt in zip(out, prompts):
        assert row.tolist() == naive_greedy(module, params, prompt, 8), prompt


def test_moe_generation_is_padding_invariant_at_tight_capacity():
    """Bucket right-padding and pow2 batch padding must not change MoE outputs:
    pad tokens are masked out of expert routing, so at the default (tight)
    capacity_factor the same prompt yields the same tokens whether it sits in a
    small bucket, a large bucket, or a batch padded with synthetic rows."""
    from unionml_tpu.models import MoEConfig, MoETransformer

    config = MoEConfig.tiny(
        vocab_size=61, dim=64, n_layers=2, n_heads=4, n_kv_heads=2, hidden_dim=96,
        n_experts=4, k=2, capacity_factor=1.25, dtype=jnp.float32, param_dtype=jnp.float32,
    )
    module = MoETransformer(config)
    params = module.init(jax.random.PRNGKey(2), jnp.zeros((1, 8), jnp.int32))["params"]
    prompt = [3, 1, 4, 1, 5]

    def run(buckets, prompts):
        gen = Generator(
            module, params,
            GenerationConfig(max_new_tokens=6, temperature=0.0, prompt_buckets=buckets),
        )
        return gen(prompts)

    small = run((8,), [prompt])
    large_bucket = run((32,), [prompt])  # 27 pad columns instead of 3
    padded_batch = run((8,), [prompt, [9, 2], [7]])  # batch pads 3 -> 4 rows
    np.testing.assert_array_equal(large_bucket, small)
    np.testing.assert_array_equal(padded_batch[:1], small)


def test_init_cache_shapes(tiny):
    _, _, config = tiny
    cache = init_cache(config, batch=2, cache_len=32)
    assert len(cache) == config.n_layers
    head_dim = config.dim // config.n_heads
    assert cache[0]["k"].shape == (2, 32, config.n_kv_heads, head_dim)
    assert cache[0]["v"].dtype == config.dtype


def test_prefix_cache_matches_full_prompt(tiny):
    """Prefix reuse must be invisible in the output: generating from (prefix +
    suffix) as one prompt and from suffix with the prefix's cached K/V rows are
    the same computation — RoPE positions continue at prefix.length and the
    pasted rows are visible to every suffix/decode query."""
    module, params, _ = tiny
    cfg = GenerationConfig(max_new_tokens=8, temperature=0.0, prompt_buckets=(8, 32))
    gen = Generator(module, params, cfg)
    prefix = [7, 7, 3, 9, 1, 2]
    suffixes = [[3, 1, 4], [9, 2, 6, 5]]

    full = gen([prefix + s for s in suffixes])
    cached = gen.cache_prefix(prefix)
    assert cached.length == len(prefix)
    np.testing.assert_array_equal(gen(suffixes, prefix=cached), full)


def test_prefix_cache_stream_matches_call(tiny):
    module, params, _ = tiny
    cfg = GenerationConfig(max_new_tokens=9, temperature=0.0, prompt_buckets=(8, 16))
    gen = Generator(module, params, cfg)
    cached = gen.cache_prefix([5, 4, 3, 2])
    suffixes = [[1, 2], [8]]
    expected = gen(suffixes, prefix=cached)
    chunks = list(gen.stream(suffixes, prefix=cached, chunk_size=4))
    np.testing.assert_array_equal(np.concatenate(chunks, axis=1), expected)


def test_prefix_cache_with_chunked_prefill_and_int8_kv(tiny):
    """Composition: the suffix flows through the chunked path (start offset =
    prefix length) and the int8-KV quantized rows paste losslessly (the prefix
    rows are already quantized, so reuse introduces no extra rounding)."""
    module, params, _ = tiny
    prefix = list(range(1, 11))
    suffixes = [[3, 1, 4, 1, 5], [9, 2]]
    for kv in (None, "int8"):
        cfg = GenerationConfig(
            max_new_tokens=6, temperature=0.0, prompt_buckets=(16,),
            prefill_chunk=4, kv_cache_dtype=kv,
        )
        gen = Generator(module, params, cfg)
        full = gen([prefix + s for s in suffixes])
        out = gen(suffixes, prefix=gen.cache_prefix(prefix))
        np.testing.assert_array_equal(out, full)


def test_prefix_cache_rejects_empty_suffix(tiny):
    module, params, _ = tiny
    gen = Generator(module, params, GenerationConfig(max_new_tokens=4, temperature=0.0))
    cached = gen.cache_prefix([5, 4, 3])
    with pytest.raises(ValueError, match="non-empty"):
        gen([[1, 2], []], prefix=cached)
