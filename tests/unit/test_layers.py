"""Layer-level oracles for the SPMD-clean embedding lookup.

``IotaEmbed`` (unionml_tpu/models/layers.py) must be a drop-in for
``nn.Embed``: identical param tree, bit-identical lookups (gather forward),
and gradients numerically equal to the scatter-add backward — only the
MECHANISM differs (one-hot matmul, which the SPMD partitioner can
reduce-scatter into a vocab-sharded table; the multichip dryrun asserts the
resulting warning-free partitioner log).
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from flax import linen as nn

from unionml_tpu.models.layers import IotaEmbed, _embed_lookup

VOCAB, DIM = 37, 16


@pytest.fixture
def table():
    return jax.random.normal(jax.random.PRNGKey(0), (VOCAB, DIM), jnp.float32)


def test_forward_is_bit_identical_to_take(table):
    tokens = jnp.asarray([[0, 3, 36, 3], [7, 7, 1, 0]], jnp.int32)
    ours = _embed_lookup(table, tokens, VOCAB)
    ref = jnp.take(table, tokens, axis=0)
    assert (ours == ref).all()


def test_backward_matches_scatter_add(table):
    tokens = jnp.asarray([[2, 5, 5, 11], [5, 0, 2, 2]], jnp.int32)
    cot = jax.random.normal(jax.random.PRNGKey(1), (2, 4, DIM), jnp.float32)

    def ours(t):
        return (_embed_lookup(t, tokens, VOCAB) * cot).sum()

    def ref(t):
        return (jnp.take(t, tokens, axis=0) * cot).sum()

    g_ours = jax.grad(ours)(table)
    g_ref = jax.grad(ref)(table)
    # repeated tokens accumulate; untouched rows stay exactly zero
    np.testing.assert_allclose(np.asarray(g_ours), np.asarray(g_ref), atol=1e-5)
    untouched = sorted(set(range(VOCAB)) - {0, 2, 5, 11})
    assert not np.asarray(g_ours)[untouched].any()


def test_module_param_tree_matches_nn_embed():
    tokens = jnp.zeros((1, 4), jnp.int32)
    ours = IotaEmbed(VOCAB, DIM, dtype=jnp.float32, param_dtype=jnp.float32)
    ref = nn.Embed(VOCAB, DIM, dtype=jnp.float32, param_dtype=jnp.float32)
    p_ours = ours.init(jax.random.PRNGKey(2), tokens)["params"]
    p_ref = ref.init(jax.random.PRNGKey(2), tokens)["params"]
    assert set(p_ours) == set(p_ref) == {"embedding"}
    assert p_ours["embedding"].shape == p_ref["embedding"].shape
    # same init distribution family and seed -> same values (drop-in for
    # checkpoints written against nn.Embed)
    np.testing.assert_allclose(
        np.asarray(p_ours["embedding"]), np.asarray(p_ref["embedding"]), atol=0
    )
    # lookups agree module-to-module
    toks = jnp.asarray([[1, 4, 9, 25]], jnp.int32)
    np.testing.assert_array_equal(
        np.asarray(ours.apply({"params": p_ours}, toks)),
        np.asarray(ref.apply({"params": p_ref}, toks)),
    )


def test_bf16_grad_dtype_follows_operand():
    table16 = jax.random.normal(jax.random.PRNGKey(3), (VOCAB, DIM), jnp.float32)
    tokens = jnp.asarray([[1, 2]], jnp.int32)

    def loss(t):
        return _embed_lookup(t.astype(jnp.bfloat16), tokens, VOCAB).astype(jnp.float32).sum()

    g = jax.grad(loss)(table16)
    assert g.dtype == jnp.float32  # the astype backward restores param dtype
    assert bool(jnp.isfinite(g).all())
