"""Disaggregated prefill/decode serving + elastic resize (docs/serving.md
"Disaggregated and elastic serving").

The pinned contracts:

- **handoff exactness**: a role-split fleet's streams (prefill replica runs
  the prefill, decode replica adopts the KV at admission-complete) are
  token-identical — the first token included — to a single mixed engine
  serving the same prompts, in dense AND paged mode;
- **zero-loss resize**: ``scale_to`` up/down mid-traffic completes every
  in-flight stream exactly (counts asserted), and the autoscaler thread is
  owned and joined by ``close()`` (the TPU008 contract, held to live);
- **decode-side radix insertion**: a finished stream's prompt + generated
  tokens publish into the prefix cache, so the next conversation turn
  cache-hits the whole prior exchange — warm output bit-identical to cold.
"""

import threading
import time

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from unionml_tpu.defaults import parse_replica_roles, serve_replica_roles
from unionml_tpu.models import GenerationConfig, Generator, Llama, LlamaConfig
from unionml_tpu.serving import ContinuousBatcher, ReplicaSet
from unionml_tpu.serving.overload import QueueFullError
from unionml_tpu.serving.replicas import ReplicaScheduler


@pytest.fixture(scope="module")
def tiny():
    config = LlamaConfig.tiny(
        vocab_size=96, dim=64, n_layers=2, n_heads=4, n_kv_heads=2, hidden_dim=128,
        dtype=jnp.float32, param_dtype=jnp.float32,
    )
    module = Llama(config)
    params = module.init(jax.random.PRNGKey(1), jnp.zeros((1, 8), jnp.int32))["params"]
    return module, params


def _cfg(**overrides):
    kwargs = dict(max_new_tokens=8, temperature=0.0, prompt_buckets=(16,))
    kwargs.update(overrides)
    return GenerationConfig(**kwargs)


PROMPTS = [[3, 1, 4, 1, 5], [9, 2, 6, 5, 3, 5, 8, 9], [7, 1], [6, 6, 6, 2]]


def _drain(stream):
    return [int(t) for chunk in stream for t in np.asarray(chunk).ravel()]


def _expected(module, params, cfg, prompts):
    gen = Generator(module, params, cfg)
    return [list(map(int, gen([p])[0])) for p in prompts]


# ------------------------------------------------------------------ knob parsing


def test_parse_replica_roles():
    assert parse_replica_roles("prefill=1,decode=3") == {"prefill": 1, "decode": 3}
    assert parse_replica_roles("decode=2, mixed=1") == {"decode": 2, "mixed": 1}
    assert parse_replica_roles("prefill=0,decode=2") == {"decode": 2}
    for bad in ("turbo=2", "prefill", "prefill=x", "prefill=-1"):
        with pytest.raises(ValueError):
            parse_replica_roles(bad)


def test_serve_replica_roles_env_degrades_on_garbage(monkeypatch, caplog):
    from unionml_tpu._logging import logger

    monkeypatch.setattr(logger, "propagate", True)
    monkeypatch.setenv("UNIONML_TPU_REPLICA_ROLES", "prefill=1,decode=3")
    assert serve_replica_roles() == {"prefill": 1, "decode": 3}
    monkeypatch.setenv("UNIONML_TPU_REPLICA_ROLES", "warp=9")
    with caplog.at_level("WARNING", logger="unionml_tpu"):
        assert serve_replica_roles() == {}
    assert any("warp=9" in record.message for record in caplog.records)
    monkeypatch.delenv("UNIONML_TPU_REPLICA_ROLES")
    assert serve_replica_roles() == {}


def test_resolve_roles_validation():
    expand = ReplicaSet._resolve_roles
    assert expand({"prefill": 1, "decode": 2}, 3) == ["prefill", "decode", "decode"]
    assert expand(["decode", "prefill"], 2) == ["decode", "prefill"]
    assert expand(None, 2) == ["mixed", "mixed"]
    with pytest.raises(ValueError):  # explicit count mismatch is a usage error
        expand({"prefill": 1, "decode": 1}, 3)
    with pytest.raises(ValueError):  # nowhere to hand decode work off to
        expand({"prefill": 2}, 2)
    with pytest.raises(ValueError):
        expand(["prefill", "turbo"], 2)


def test_resolve_roles_env_mismatch_degrades(monkeypatch, caplog):
    from unionml_tpu._logging import logger

    monkeypatch.setattr(logger, "propagate", True)
    monkeypatch.setenv("UNIONML_TPU_REPLICA_ROLES", "prefill=1,decode=3")
    with caplog.at_level("WARNING", logger="unionml_tpu"):
        assert ReplicaSet._resolve_roles(None, 2) == ["mixed", "mixed"]
    assert any("symmetric" in record.message for record in caplog.records)


# ------------------------------------------------------------------ scheduler


def test_scheduler_deprioritizes_prefill_replicas():
    sched = ReplicaScheduler(3)
    # replica 0 is idle but prefill-role: decode work goes to 1 (less loaded
    # of the unflagged), and the flagged replica stays in the walk order
    order, affinity = sched.order([0.0, 1.0, 2.0], deprioritized=[True, False, False])
    assert order == [1, 2, 0] and not affinity
    # everyone flagged degrades to plain least-loaded
    order, _ = sched.order([1.0, 0.0], deprioritized=[True, True])
    assert order == [1, 0]


def test_scheduler_resize_keeps_counts_and_bounds():
    sched = ReplicaScheduler(2, affinity_tokens=2)
    sched.note(0, [1, 2, 3])
    sched.note(1, [4, 5, 6])
    sched.resize(4)
    assert sched.stats()["submitted"] == [1, 1, 0, 0]
    sched.note(3)
    sched.resize(1)
    stats = sched.stats()
    assert stats["submitted"] == [1]
    # affinity entries pointing at removed replicas are dropped
    order, affinity = sched.order([0.0], [4, 5, 6])
    assert not affinity
    with pytest.raises(ValueError):
        sched.resize(0)


# ------------------------------------------------------------------ handoff


def test_role_split_fleet_token_identical_dense(tiny):
    module, params = tiny
    cfg = _cfg()
    expected = _expected(module, params, cfg, PROMPTS)
    fleet = ReplicaSet.build(
        module, params, cfg, replicas=2, roles={"prefill": 1, "decode": 1},
        slots=2, decode_chunk=4, prefill_threshold=0,
    )
    try:
        assert fleet.roles == ["prefill", "decode"]
        got = [_drain(fleet.submit(p)) for p in PROMPTS]
        assert got == expected  # first token included: the handoff is exact
        stats = fleet.stats()
        assert stats["roles"] == {"prefill": 1, "decode": 1, "mixed": 0}
        assert stats["handoffs"]["routed"] == len(PROMPTS)
        assert stats["handoffs"]["exported"] == len(PROMPTS)
        assert stats["handoffs"]["imported"] == len(PROMPTS)
        prefill_stats, decode_stats = stats["per_replica"]
        assert prefill_stats["role"] == "prefill" and decode_stats["role"] == "decode"
        assert prefill_stats["handoff"]["exported"] == len(PROMPTS)
        assert decode_stats["handoff"]["imported"] == len(PROMPTS)
        assert decode_stats["handoff"]["transfer_ms"]["window"] == len(PROMPTS)
        # every decoded token ran on the decode replica; the prefill replica
        # never spent a decode dispatch on these streams
        assert prefill_stats["decode_dispatches"] == 0
        assert [entry["role"] for entry in fleet.replica_loads()] == ["prefill", "decode"]
    finally:
        fleet.close()


def test_role_split_fleet_paged_with_multi_turn_shortcut(tiny):
    module, params = tiny
    cfg = _cfg(prompt_buckets=(32,))
    prompt = [3, 1, 4, 1, 5, 9, 2, 6]
    fleet = ReplicaSet.build(
        module, params, cfg, replicas=2, roles={"prefill": 1, "decode": 1},
        slots=2, decode_chunk=4, block_size=4, prefix_cache=True, prefill_threshold=0,
    )
    try:
        generated = _drain(fleet.submit(prompt))
        assert generated == _expected(module, params, cfg, [prompt])[0]
        # turn 2 extends the whole prior exchange; the decode replica's radix
        # cache (prompt published at import, generation published at finish)
        # covers it, so the fleet admits there DIRECTLY — no second prefill
        # replica round-trip — and the output still equals a cold run
        turn2 = prompt + generated + [5, 7]
        warm = _drain(fleet.submit(turn2))
        assert warm == _expected(module, params, cfg, [turn2])[0]
        stats = fleet.stats()
        assert stats["handoffs"]["routed"] == 1
        assert stats["handoffs"]["shortcuts"] == 1
        decode_stats = stats["per_replica"][1]
        assert decode_stats["prefix_cache"]["hits"] == 1
        assert decode_stats["prefix_cache"]["tokens_avoided"] > len(prompt)
    finally:
        fleet.close()


def test_export_finishes_outright_without_handoff(tiny):
    module, params = tiny
    cfg = _cfg()
    fleet = ReplicaSet.build(
        module, params, cfg, replicas=2, roles={"prefill": 1, "decode": 1},
        slots=2, decode_chunk=4, prefill_threshold=0,
    )
    try:
        # budget 1: the prompt-sampled token IS the stream — the prefill
        # replica finishes it locally, nothing crosses to the decode replica
        tokens = _drain(fleet.submit(PROMPTS[0], max_new_tokens=1))
        assert tokens == _expected(module, params, cfg, [PROMPTS[0]])[0][:1]
        stats = fleet.stats()
        assert stats["handoffs"]["exported"] == 0
        assert stats["handoffs"]["imported"] == 0
    finally:
        fleet.close()


def test_short_prompts_skip_the_prefill_tier(tiny):
    module, params = tiny
    cfg = _cfg()
    fleet = ReplicaSet.build(
        module, params, cfg, replicas=2, roles={"prefill": 1, "decode": 1},
        slots=2, decode_chunk=4, prefill_threshold=6,
    )
    try:
        short, long_ = [7, 1], [9, 2, 6, 5, 3, 5, 8, 9]
        assert _drain(fleet.submit(short)) == _expected(module, params, cfg, [short])[0]
        assert _drain(fleet.submit(long_)) == _expected(module, params, cfg, [long_])[0]
        stats = fleet.stats()
        # only the >= threshold prompt disaggregated; the short one admitted
        # directly on the (deprioritized-last walk's) decode replica
        assert stats["handoffs"]["routed"] == 1
        assert stats["per_replica"][1]["handoff"]["imported"] == 1
    finally:
        fleet.close()


def test_export_requires_no_speculative_and_handoff_attr_surface(tiny):
    module, params = tiny
    engine = ContinuousBatcher._single(
        Generator(module, params, _cfg()), slots=2, decode_chunk=4, role="prefill"
    )
    try:
        stream = engine.submit(PROMPTS[0], export_handoff=True)
        first = _drain(stream)
        assert len(first) == 1
        payload = stream.handoff
        assert payload is not None
        assert payload["first"] == first[0]
        assert payload["prompt"] == PROMPTS[0]
        assert payload["produced"] == 1 and payload["echo"] == first
        stats = engine.stats()
        assert stats["role"] == "prefill" and stats["handoff"]["exported"] == 1
    finally:
        engine.close()
    with pytest.raises(ValueError):
        ContinuousBatcher._single(Generator(module, params, _cfg()), role="turbo")


def test_quiesced_engine_sheds_and_keeps_draining(tiny):
    module, params = tiny
    engine = ContinuousBatcher._single(Generator(module, params, _cfg()), slots=2)
    try:
        stream = engine.submit(PROMPTS[0])
        engine.quiesce()
        with pytest.raises(QueueFullError):
            engine.submit(PROMPTS[1])
        # already-submitted work drains to completion regardless
        assert _drain(stream) == _expected(module, params, _cfg(), [PROMPTS[0]])[0]
    finally:
        engine.close()


# ------------------------------------------------------ decode-side insertion


def test_decode_side_insertion_warm_equals_cold(tiny):
    module, params = tiny
    cfg = _cfg(prompt_buckets=(32,))
    engine = ContinuousBatcher._single(
        Generator(module, params, cfg), slots=2, decode_chunk=4,
        block_size=4, pool_blocks=64, prefix_cache=True,
    )
    try:
        prompt = [3, 1, 4, 1, 5, 9, 2, 6]  # two full blocks
        generated = _drain(engine.submit(prompt))
        assert len(generated) == 8
        # prompt(8) + generated-with-written-KV(7) = 15 -> 3 full blocks: one
        # MORE than the prompt-only publish at finalize could cover
        turn2 = prompt + generated + [5, 7]
        cached = engine.cached_prefix_tokens(turn2)
        assert cached > len(prompt)
        cold = _expected(module, params, cfg, [turn2])[0]
        warm = _drain(engine.submit(turn2))
        assert warm == cold
        stats = engine.stats()["prefix_cache"]
        assert stats["hits"] == 1 and stats["tokens_avoided"] == cached
    finally:
        engine.close()


# ------------------------------------------------------------------ elasticity


def test_scale_to_zero_loss_mid_traffic(tiny):
    module, params = tiny
    cfg = _cfg()
    rng = np.random.default_rng(0)
    prompts = [list(map(int, rng.integers(1, 96, size=int(rng.integers(2, 10))))) for _ in range(10)]
    expected = _expected(module, params, cfg, prompts)
    fleet = ReplicaSet.build(module, params, cfg, replicas=1, slots=2, decode_chunk=4)
    try:
        results = [None] * len(prompts)

        def worker(i):
            results[i] = _drain(fleet.submit(prompts[i]))

        first_wave = [threading.Thread(target=worker, args=(i,)) for i in range(5)]
        for t in first_wave:
            t.start()
        assert fleet.scale_to(2) == 2
        assert fleet.replicas == 2
        second_wave = [threading.Thread(target=worker, args=(i,)) for i in range(5, 10)]
        for t in second_wave:
            t.start()
        assert fleet.scale_to(1) == 1
        assert fleet.replicas == 1
        for t in first_wave + second_wave:
            t.join(timeout=180)
        # zero lost streams: every submission completed with exact tokens
        assert results == expected
        stats = fleet.stats()
        assert sum(stats["scheduler"]["submitted"][:1]) <= len(prompts)
        assert stats["resize"]["scaled_up"] == 1 and stats["resize"]["scaled_down"] == 1
    finally:
        fleet.close()


def test_scale_guards(tiny):
    module, params = tiny
    cfg = _cfg()
    fleet = ReplicaSet.build(module, params, cfg, replicas=1, slots=2)
    try:
        with pytest.raises(ValueError):
            fleet.scale_to(0)
        assert fleet.spare_capacity() > 0  # mesh-less: round-robin placement
    finally:
        fleet.close()
    # a set built from pre-made generators retains no construction template
    bare = ReplicaSet(
        [Generator(module, params, cfg), Generator(module, params, cfg)],
        slots=2,
    )
    try:
        assert bare.spare_capacity() == 0
        with pytest.raises(RuntimeError):
            bare.scale_to(3)
        bare.scale_to(1)  # shrinking needs no template
        assert bare.replicas == 1
    finally:
        bare.close()


def test_autoscaler_scales_on_pressure_and_close_joins(tiny, monkeypatch):
    module, params = tiny
    cfg = _cfg()
    fleet = ReplicaSet.build(module, params, cfg, replicas=1, slots=2)
    try:
        pressure = {"value": 10.0}
        monkeypatch.setattr(
            type(fleet), "_autoscale_pressure", lambda self: pressure["value"]
        )
        fleet.configure_autoscaler(high=1.0, low=0.5, interval_s=0.05, min_replicas=1)
        deadline = time.monotonic() + 60.0
        while fleet.replicas < 2 and time.monotonic() < deadline:
            time.sleep(0.05)
        assert fleet.replicas >= 2
        pressure["value"] = 0.0
        while fleet.replicas > 1 and time.monotonic() < deadline:
            time.sleep(0.05)
        assert fleet.replicas == 1
        stats = fleet.stats()
        assert stats["resize"]["scaled_up"] >= 1 and stats["resize"]["scaled_down"] >= 1
        assert stats["resize"]["autoscaler"]["high"] == 1.0
        thread = fleet._autoscale_thread
    finally:
        fleet.close()
    assert thread is not None and not thread.is_alive()  # TPU008, held to live


def test_configure_autoscaler_validation(tiny):
    module, params = tiny
    fleet = ReplicaSet.build(module, params, _cfg(), replicas=1, slots=2, autoscale=False)
    try:
        for kwargs in (
            dict(high=0.0),
            dict(high=1.0, low=2.0),
            dict(high=1.0, interval_s=0.0),
            dict(high=1.0, min_replicas=0),
            dict(high=1.0, role="turbo"),
        ):
            with pytest.raises(ValueError):
                fleet.configure_autoscaler(**kwargs)
    finally:
        fleet.close()


# ---------------------------------------------------------------- app surface


class _FakeEngine:
    role = "decode"

    def health(self):
        return {"score": 1.0, "state": "ok", "state_code": 0, "enabled": False}


class _FakeFleet:
    def __init__(self):
        self.batchers = (_FakeEngine(),)
        self.calls = []

    def scale_to(self, n, role=None):
        if n > 4:
            raise RuntimeError("no spare submesh")
        self.calls.append((n, role))
        return n


def test_debug_scale_endpoint(sklearn_model):
    import asyncio

    sklearn_model.train(hyperparameters={"max_iter": 500})
    from unionml_tpu.serving.app import ServingApp

    app = ServingApp(sklearn_model)

    def dispatch(method, path, body=b""):
        async def run():
            app.startup()
            return await app.server.dispatch(method, path, body)

        return asyncio.run(run())

    status, payload, _ = dispatch("POST", "/debug/scale", b'{"replicas": 2}')
    assert status == 400  # no elastic generation fleet on this app
    fleet = _FakeFleet()
    sklearn_model.generation_batcher = fleet
    try:
        status, payload, _ = dispatch("POST", "/debug/scale", b'{"replicas": 3, "role": "decode"}')
        assert status == 200 and payload["replicas"] == 3
        assert fleet.calls == [(3, "decode")]
        # the role census rides the health payload for role-split fleets
        assert payload["health"]["replicas"][0]["role"] == "decode"
        status, payload, _ = dispatch("POST", "/debug/scale", b'{"replicas": 0}')
        assert status == 400
        status, payload, _ = dispatch("POST", "/debug/scale", b'{"replicas": 9}')
        assert status == 400 and "spare" in payload["detail"]
        status, payload, _ = dispatch("POST", "/debug/scale", b'{"replicas": 2, "role": "turbo"}')
        assert status == 400
    finally:
        del sklearn_model.generation_batcher


def test_replica_roles_env_drives_engine_delegation(tiny, monkeypatch):
    module, params = tiny
    monkeypatch.delenv("UNIONML_TPU_DP_REPLICAS", raising=False)
    monkeypatch.setenv("UNIONML_TPU_REPLICA_ROLES", "prefill=1,decode=1")
    monkeypatch.setenv("UNIONML_TPU_PREFILL_THRESHOLD", "0")
    fleet = ContinuousBatcher(Generator(module, params, _cfg()), slots=2, decode_chunk=4)
    try:
        # --replica-roles alone implies the fleet size and the role split,
        # through the same transparent delegation --dp-replicas uses
        assert isinstance(fleet, ReplicaSet)
        assert fleet.roles == ["prefill", "decode"]
        prompt = PROMPTS[0]
        assert _drain(fleet.submit(prompt)) == _expected(module, params, _cfg(), [prompt])[0]
        assert fleet.stats()["handoffs"]["exported"] == 1
    finally:
        fleet.close()
