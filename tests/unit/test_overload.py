"""Overload-protection behavior: admission control, deadlines, load shedding,
and graceful drain (serving/overload.py + the bounded queues it feeds).

The oracle throughout: with admission cap Q and a wedged predictor, a 4xQ
flood leaves AT MOST Q requests queued-or-in-flight and sheds the rest
immediately with 429 + Retry-After; deadline-expired work is shed with 503
without spending a predictor dispatch; a draining server answers
503/ready=false while in-flight work finishes. Continuous-engine overload
tests (slot-wait bounds, disconnect-frees-slot) live in test_continuous.py,
next to the engine fixtures they reuse.
"""

import asyncio
import json
import threading
import time

import pytest

from unionml_tpu.serving import (
    DeadlineExceeded,
    MicroBatcher,
    QueueFullError,
    ServingConfig,
    serving_app,
)
from unionml_tpu.serving.http import _STATUS_PHRASES, HTTPError, HTTPServer


# ------------------------------------------------------------------ HTTP layer


def test_shed_status_phrases_exist():
    """429/503 responses must carry real reason phrases, not 'Unknown'."""
    assert _STATUS_PHRASES[429] == "Too Many Requests"
    assert _STATUS_PHRASES[503] == "Service Unavailable"
    assert _STATUS_PHRASES[408] == "Request Timeout"


def test_negative_content_length_is_a_clean_400():
    """A negative Content-Length must be rejected at the parser, not passed to
    readexactly (whose own ValueError message is about internals)."""
    server = HTTPServer()

    async def scenario():
        reader = asyncio.StreamReader()
        reader.feed_data(b"POST /predict HTTP/1.1\r\nContent-Length: -5\r\n\r\n")
        reader.feed_eof()
        with pytest.raises(ValueError, match="negative Content-Length"):
            await server._read_request(reader)
        reader = asyncio.StreamReader()
        reader.feed_data(b"POST /predict HTTP/1.1\r\nContent-Length: nope\r\n\r\n")
        reader.feed_eof()
        with pytest.raises(ValueError, match="malformed Content-Length"):
            await server._read_request(reader)

    asyncio.run(scenario())


def test_inflight_cap_sheds_excess_with_429_and_retry_after():
    """Admission control at the HTTP layer: cap Q, flood 4xQ against a blocked
    handler -> exactly Q admitted (in flight), 3xQ shed IMMEDIATELY with 429 +
    Retry-After; once unblocked, the admitted Q all complete."""
    Q = 4
    server = HTTPServer()
    server.max_inflight = Q
    release = asyncio.Event()

    async def handler(body):
        await release.wait()
        return 200, {"ok": True}, "application/json"

    server.route("POST", "/work", handler)

    async def scenario():
        tasks = [
            asyncio.create_task(server._dispatch_full("POST", "/work", b""))
            for _ in range(4 * Q)
        ]
        await asyncio.sleep(0.05)  # one scheduling tick: sheds are synchronous
        done = [t for t in tasks if t.done()]
        shed = [t.result() for t in done]
        assert len(shed) == 3 * Q, "excess requests must shed within one tick"
        assert all(r[0] == 429 for r in shed)
        assert all(r[3].get("Retry-After") for r in shed)
        assert server.inflight == Q  # bounded in-flight, nothing queued beyond
        release.set()
        results = await asyncio.gather(*tasks)
        assert sum(1 for r in results if r[0] == 200) == Q
        assert server.inflight == 0

    asyncio.run(scenario())


def test_deadline_header_cancels_slow_handler_with_503():
    server = HTTPServer()
    cancelled = asyncio.Event()

    async def slow(body):
        try:
            await asyncio.sleep(30)
        except asyncio.CancelledError:
            cancelled.set()  # resources reclaimed, not leaked
            raise
        return 200, {}, "application/json"

    server.route("POST", "/slow", slow)

    async def scenario():
        t0 = time.monotonic()
        status, payload, _ = await server.dispatch(
            "POST", "/slow", b"", {"x-request-deadline-ms": "50"}
        )
        assert status == 503
        assert time.monotonic() - t0 < 5.0  # the deadline fired, not the sleep
        await asyncio.wait_for(cancelled.wait(), 2.0)
        # born-expired: non-positive deadline sheds before the handler runs
        status, payload, _ = await server.dispatch(
            "POST", "/slow", b"", {"x-request-deadline-ms": "0"}
        )
        assert status == 503 and "deadline" in payload["detail"]
        # malformed header is the client's fault: 400, not a silent default
        status, payload, _ = await server.dispatch(
            "POST", "/slow", b"", {"x-request-deadline-ms": "soon"}
        )
        assert status == 400

    asyncio.run(scenario())


def test_server_default_deadline_applies_without_header():
    server = HTTPServer()
    server.default_deadline_ms = 50

    async def slow(body):
        await asyncio.sleep(30)
        return 200, {}, "application/json"

    server.route("POST", "/slow", slow)
    status, payload, _ = asyncio.run(server.dispatch("POST", "/slow", b""))
    assert status == 503


def test_client_deadline_is_clipped_to_server_max():
    server = HTTPServer()
    server.max_deadline_ms = 50  # a client cannot pin resources past this

    async def slow(body):
        await asyncio.sleep(30)
        return 200, {}, "application/json"

    server.route("POST", "/slow", slow)
    status, *_ = asyncio.run(
        server.dispatch("POST", "/slow", b"", {"x-request-deadline-ms": "600000"})
    )
    assert status == 503


def test_queue_full_error_from_handler_maps_to_429():
    server = HTTPServer()

    async def full(body):
        raise QueueFullError("engine queue full", retry_after_s=7)

    server.route("POST", "/gen", full)

    async def scenario():
        status, payload, _, extra, _ = await server._dispatch_full("POST", "/gen", b"")
        assert status == 429
        assert extra["Retry-After"] == "7"

    asyncio.run(scenario())


def test_http_error_headers_reach_the_wire_encoding():
    raw = HTTPServer._encode_response(
        429, {"detail": "full"}, keep_alive=False, extra_headers={"Retry-After": "3"}
    )
    head = raw.split(b"\r\n\r\n")[0].decode()
    assert "429 Too Many Requests" in head and "Retry-After: 3" in head
    assert isinstance(HTTPError(429, "x", headers={"Retry-After": "1"}).headers, dict)


# ------------------------------------------------------------------ drain


def test_drain_sheds_new_work_but_health_and_metrics_stay_up():
    server = HTTPServer()

    async def work(body):
        return 200, {"ok": True}, "application/json"

    async def health(body):
        if server.draining:
            return 503, {"ready": False}, "application/json"
        return 200, {"ready": True}, "application/json"

    async def metrics(body):
        return 200, {}, "application/json"

    server.route("POST", "/work", work)
    server.route("GET", "/health", health)
    server.route("GET", "/metrics", metrics)

    async def scenario():
        assert (await server.dispatch("POST", "/work", b""))[0] == 200
        server.begin_drain()
        status, payload, _, extra, _ = await server._dispatch_full("POST", "/work", b"")
        assert status == 503 and "draining" in payload["detail"]
        assert extra.get("Retry-After")
        # exempt probes keep answering so the LB sees ready=false, not a dead host
        status, payload, _ = await server.dispatch("GET", "/health", b"")
        assert status == 503 and payload["ready"] is False
        assert (await server.dispatch("GET", "/metrics", b""))[0] == 200

    asyncio.run(scenario())


def test_shutdown_waits_for_inflight_work_then_signals_stop():
    """The SIGTERM path (serve() wires SIGTERM -> shutdown()): in-flight work
    admitted before the drain completes normally; the drain returns only after
    it finishes (or the drain timeout expires)."""
    server = HTTPServer()
    drained = []
    server.on_drained = lambda: drained.append(True)

    async def slowish(body):
        await asyncio.sleep(0.2)
        return 200, {"ok": True}, "application/json"

    server.route("POST", "/work", slowish)

    async def scenario():
        inflight = asyncio.create_task(server.dispatch("POST", "/work", b""))
        await asyncio.sleep(0.02)  # the request is mid-handler when SIGTERM lands
        t0 = time.monotonic()
        await server.shutdown(drain_timeout_s=5.0)
        assert time.monotonic() - t0 >= 0.1  # waited for the in-flight request
        status, *_ = inflight.result()  # finished cleanly during the drain
        assert status == 200
        assert drained == [True]
        # late arrivals during/after the drain are shed
        assert (await server.dispatch("POST", "/work", b""))[0] == 503

    asyncio.run(scenario())


# ------------------------------------------------------------------ micro-batcher


def test_micro_batcher_full_queue_sheds_immediately():
    """Bounded admission queue: with the predictor wedged and max_queue=Q, a
    4xQ flood keeps at most Q queued (+ one dispatching batch) and sheds the
    rest synchronously with QueueFullError."""
    Q = 4
    release = threading.Event()

    def predict(batch):
        release.wait(timeout=30)
        return [x * 2 for x in batch]

    async def scenario():
        batcher = MicroBatcher(
            predict,
            ServingConfig(max_batch_size=2, max_wait_ms=1, pad_to_bucket=False, max_queue=Q),
        )
        tasks = [asyncio.create_task(batcher.submit([i])) for i in range(4 * Q)]
        await asyncio.sleep(0.05)
        shed = [
            t for t in tasks if t.done() and isinstance(t.exception(), QueueFullError)
        ]
        # worker absorbs at most one batch (max_batch_size=2); queue holds <= Q
        assert len(shed) >= 4 * Q - Q - 2
        assert batcher.queue_depth <= Q
        assert batcher.stats()["shed_queue_full"] == len(shed)
        release.set()
        results = await asyncio.gather(*tasks, return_exceptions=True)
        served = [r for r in results if isinstance(r, list)]
        assert len(served) == 4 * Q - len(shed)  # every admitted request answered
        await batcher.stop()

    asyncio.run(scenario())


def test_micro_batcher_sheds_expired_queued_request_without_dispatching_it():
    dispatched = []
    release = threading.Event()

    def predict(batch):
        dispatched.append(list(batch))
        release.wait(timeout=30)
        return [x * 2 for x in batch]

    async def scenario():
        batcher = MicroBatcher(
            predict, ServingConfig(max_batch_size=1, max_wait_ms=1, pad_to_bucket=False)
        )
        blocker = asyncio.create_task(batcher.submit([1]))
        await asyncio.sleep(0.05)  # the wedged dispatch now owns the worker
        doomed = asyncio.create_task(
            batcher.submit([2], deadline=time.monotonic() + 0.05)
        )
        await asyncio.sleep(0.15)  # expires while queued behind the wedge
        release.set()
        assert (await blocker) == [2]
        with pytest.raises(DeadlineExceeded):
            await doomed
        assert [1] in dispatched and [2] not in dispatched  # no wasted dispatch
        assert batcher.stats()["shed_deadline"] == 1
        await batcher.stop()

    asyncio.run(scenario())


def test_micro_batcher_reaps_cancelled_requests_before_dispatch():
    """A handler cancelled at the HTTP layer (client disconnect / deadline)
    leaves a done future in the queue; the worker must drop it instead of
    spending a predictor dispatch on it."""
    dispatched = []
    release = threading.Event()

    def predict(batch):
        dispatched.append(list(batch))
        release.wait(timeout=30)
        return [x * 2 for x in batch]

    async def scenario():
        batcher = MicroBatcher(
            predict, ServingConfig(max_batch_size=1, max_wait_ms=1, pad_to_bucket=False)
        )
        blocker = asyncio.create_task(batcher.submit([1]))
        await asyncio.sleep(0.05)
        abandoned = asyncio.create_task(batcher.submit([2]))
        await asyncio.sleep(0.02)
        abandoned.cancel()  # the disconnecting client
        await asyncio.sleep(0.02)
        release.set()
        assert (await blocker) == [2]
        with pytest.raises(asyncio.CancelledError):
            await abandoned
        # give the worker a tick to reap the cancelled item, then verify
        await asyncio.sleep(0.05)
        assert [2] not in dispatched
        assert batcher.stats()["cancelled"] == 1
        await batcher.stop()

    asyncio.run(scenario())


# ------------------------------------------------------------------ end to end


def test_app_flood_bounded_admission_and_drain(sklearn_model):
    """The acceptance scenario, in process: admission cap Q, wedged predictor,
    4xQ flood -> <=Q queued+in-flight, 3xQ shed with 429 + Retry-After within a
    tick; /metrics reports the sheds; a drain then flips /health readiness and
    sheds new predicts with 503 while admitted work completes."""
    Q = 4
    sklearn_model.train(hyperparameters={"max_iter": 500})
    app = serving_app(sklearn_model)
    app.configure_overload(max_inflight=Q)
    app.startup()

    release = threading.Event()
    fast_predict = app.batcher._predict_fn

    def wedged(features):
        release.wait(timeout=30)
        return fast_predict(features)

    app.batcher._predict_fn = wedged
    body = json.dumps({"features": [{"x1": 1.0, "x2": 1.0}]}).encode()

    async def scenario():
        tasks = [
            asyncio.create_task(app.server._dispatch_full("POST", "/predict", body))
            for _ in range(4 * Q)
        ]
        await asyncio.sleep(0.1)  # one tick: every shed is already resolved
        done = [t.result() for t in tasks if t.done()]
        assert len(done) == 3 * Q
        assert all(r[0] == 429 and r[3].get("Retry-After") for r in done)
        assert app.server.inflight == Q
        assert app.batcher.queue_depth <= Q  # bounded queue behind the cap
        release.set()
        results = await asyncio.gather(*tasks)
        assert sum(1 for r in results if r[0] == 200) == Q

        status, snapshot, _ = await app.dispatch("GET", "/metrics")
        assert snapshot["overload"]["shed_inflight"] == 3 * Q
        assert "inflight" in snapshot["gauges"]
        assert snapshot["micro_batcher"]["max_queue"] > 0

        # ---- graceful drain: readiness flips, new predicts shed, probes live
        status, payload, _ = await app.dispatch("GET", "/health")
        assert status == 200 and payload["ready"] is True
        app.server.begin_drain()
        status, payload, _ = await app.dispatch("GET", "/health")
        assert status == 503 and payload["ready"] is False
        status, payload, _, extra, _ = await app.server._dispatch_full(
            "POST", "/predict", body
        )
        assert status == 503 and extra.get("Retry-After")
        assert (await app.dispatch("GET", "/metrics"))[0] == 200
        await app.server.shutdown(drain_timeout_s=1.0)

    asyncio.run(scenario())


def test_app_request_deadline_propagates_to_batcher_shed(sklearn_model):
    """An explicit client deadline rides the contextvar into the micro-batcher:
    a request expiring while queued behind a wedge is answered 503 and its
    queued work is reaped, never dispatched."""
    sklearn_model.train(hyperparameters={"max_iter": 500})
    app = serving_app(sklearn_model)
    app.startup()

    release = threading.Event()
    fast_predict = app.batcher._predict_fn
    seen_x1 = []

    def wedged(features):
        seen_x1.extend(float(v) for v in features["x1"])
        release.wait(timeout=30)
        return fast_predict(features)

    app.batcher._predict_fn = wedged
    body = json.dumps({"features": [{"x1": 1.0, "x2": 1.0}]}).encode()
    doomed_body = json.dumps({"features": [{"x1": 99.0, "x2": 1.0}]}).encode()

    async def scenario():
        blocker = asyncio.create_task(app.dispatch("POST", "/predict", body))
        await asyncio.sleep(0.1)  # the wedge owns the dispatch loop
        status, payload, _ = await app.dispatch(
            "POST", "/predict", doomed_body, {"x-request-deadline-ms": "50"}
        )
        assert status == 503
        release.set()
        assert (await blocker)[0] == 200
        # the expired request's rows never reached the predictor: its queued
        # work was reaped (cancelled future / expired deadline) at dequeue
        await asyncio.sleep(0.05)
        assert 99.0 not in seen_x1

    asyncio.run(scenario())
