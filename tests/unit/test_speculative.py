"""Speculative decoding exactness: output must equal target-only greedy decoding
regardless of the draft model — a good draft only changes speed, never tokens."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from unionml_tpu.models import GenerationConfig, Generator, Llama, LlamaConfig, SpeculativeGenerator


def _model(seed: int, n_layers: int = 2, dim: int = 64):
    config = LlamaConfig.tiny(
        vocab_size=97, dim=dim, n_layers=n_layers, n_heads=4, n_kv_heads=2,
        hidden_dim=2 * dim, dtype=jnp.float32, param_dtype=jnp.float32,
    )
    module = Llama(config)
    params = module.init(jax.random.PRNGKey(seed), jnp.zeros((1, 8), jnp.int32))["params"]
    return module, params


PROMPTS = [[3, 14, 15, 92, 6], [27, 1], [8, 2, 8, 1, 8, 2, 8], [44, 9]]


@pytest.mark.parametrize("gamma", [1, 3, 5])
def test_disagreeing_draft_still_exact(gamma):
    """An unrelated (random) draft disagrees almost always — acceptance ~0 — yet
    the emitted tokens must be exactly the target's greedy sequence."""
    target, tp = _model(0)
    draft, dp = _model(123, n_layers=1, dim=32)
    cfg = GenerationConfig(max_new_tokens=10, temperature=0.0, prompt_buckets=(16,))

    expected = Generator(target, tp, cfg)(PROMPTS)
    spec = SpeculativeGenerator(target, tp, draft, dp, cfg, gamma=gamma)
    np.testing.assert_array_equal(spec(PROMPTS), expected)
    assert spec.rounds >= 1


def test_perfect_draft_is_exact_and_accepts():
    """Draft == target: proposals mostly accept (not always — the [B,1] draft
    forward and [B,gamma+1] verify forward can differ by an ulp and flip a
    near-tie argmax), so rounds land well below one-per-token and the output
    is still exact."""
    target, tp = _model(0)
    cfg = GenerationConfig(max_new_tokens=12, temperature=0.0, prompt_buckets=(16,))

    expected = Generator(target, tp, cfg)(PROMPTS)
    spec = SpeculativeGenerator(target, tp, target, tp, cfg, gamma=3)
    np.testing.assert_array_equal(spec(PROMPTS), expected)
    # 11 post-prefill tokens: all-accept needs 3 rounds, one-per-token needs 11
    assert spec.rounds <= 8
    assert spec.accepted_tokens >= spec.rounds  # acceptance is clearly happening


def test_eos_truncates_exactly_like_plain_decoding():
    target, tp = _model(0)
    draft, dp = _model(7, n_layers=1, dim=32)
    free = Generator(
        target, tp, GenerationConfig(max_new_tokens=10, temperature=0.0, prompt_buckets=(16,))
    )(PROMPTS)
    eos = int(free[0][2])  # force an eos mid-sequence for row 0
    cfg = GenerationConfig(max_new_tokens=10, temperature=0.0, prompt_buckets=(16,), eos_id=eos, pad_id=0)

    expected = Generator(target, tp, cfg)(PROMPTS)
    spec = SpeculativeGenerator(target, tp, draft, dp, cfg, gamma=4)
    np.testing.assert_array_equal(spec(PROMPTS), expected)


def test_speculative_sampling_matches_target_distribution():
    """Rejection sampling must leave the output distribution exactly the
    target's, independent of the draft. Compare empirical second-token
    distributions (the first speculated position) between plain Generator
    sampling and speculative sampling with an unrelated draft, over many seeds."""
    target, tp = _model(0, dim=32)
    draft, dp = _model(99, n_layers=1, dim=32)
    # top_k=4 concentrates the support so two same-distribution 400-draws sit at
    # TV ~0.05 while a draft-biased sampler would sit far above the threshold
    # (full-vocab support would put the NOISE floor at ~0.26 — underpowered)
    cfg = GenerationConfig(max_new_tokens=2, temperature=1.0, top_k=4, prompt_buckets=(8,))
    prompt = [[3, 14, 15]]
    n_seeds = 400

    plain = Generator(target, tp, cfg)
    spec = SpeculativeGenerator(target, tp, draft, dp, cfg, gamma=2)

    plain_counts: dict = {}
    spec_counts: dict = {}
    for s in range(n_seeds):
        t = int(plain(prompt, seed=s)[0][1])
        plain_counts[t] = plain_counts.get(t, 0) + 1
        t = int(spec(prompt, seed=s)[0][1])
        spec_counts[t] = spec_counts.get(t, 0) + 1

    support = set(plain_counts) | set(spec_counts)
    tv = 0.5 * sum(
        abs(plain_counts.get(t, 0) - spec_counts.get(t, 0)) / n_seeds for t in support
    )
    # total-variation distance between two 400-sample draws of the same 4-point
    # distribution concentrates around ~0.05; a biased sampler does not
    assert tv < 0.12, (tv, plain_counts, spec_counts)


def test_speculative_sampling_is_seed_deterministic():
    target, tp = _model(0, dim=32)
    draft, dp = _model(7, n_layers=1, dim=32)
    cfg = GenerationConfig(max_new_tokens=6, temperature=0.9, top_k=30, prompt_buckets=(16,))
    spec = SpeculativeGenerator(target, tp, draft, dp, cfg, gamma=3)
    a = spec(PROMPTS, seed=11)
    b = spec(PROMPTS, seed=11)
    np.testing.assert_array_equal(a, b)
    assert (spec(PROMPTS, seed=12) != a).any()


def test_perfect_draft_long_horizon_acceptance():
    """Draft-cache completeness: every accepted draft token's K/V must land in
    the draft cache (including the last draft of an all-accept round, which the
    scan itself never feeds). With holes, a perfect draft's acceptance decays
    as zero-initialized slots stay visible to later queries; with a complete
    cache the rounds count stays near the all-accept ideal."""
    target, tp = _model(0)
    cfg = GenerationConfig(max_new_tokens=40, temperature=0.0, prompt_buckets=(16,))
    expected = Generator(target, tp, cfg)(PROMPTS)
    spec = SpeculativeGenerator(target, tp, target, tp, cfg, gamma=4)
    np.testing.assert_array_equal(spec(PROMPTS), expected)
    # 39 post-prefill tokens at gamma=4: all-accept needs 8 rounds; leave slack
    # only for ulp-level argmax flips between the [B,1] and [B,gamma+1] forwards
    assert spec.rounds <= 14, spec.rounds


def test_moe_target_verifies_with_routed_experts():
    """The [B, gamma+1] verify forward must trace through a routed decoder: the
    token_mask broadcasts to the verify width (a [B, 1] mask used to fail at
    trace time), and greedy output equals the MoE target's own decode."""
    from unionml_tpu.models import MoEConfig, MoETransformer

    config = MoEConfig.tiny(
        vocab_size=61, dim=64, n_layers=2, n_heads=4, n_kv_heads=2, hidden_dim=96,
        n_experts=4, k=2, capacity_factor=8.0, dtype=jnp.float32, param_dtype=jnp.float32,
    )
    module = MoETransformer(config)
    params = module.init(jax.random.PRNGKey(2), jnp.zeros((1, 8), jnp.int32))["params"]
    draft_cfg = LlamaConfig.tiny(
        vocab_size=61, dim=32, n_layers=1, n_heads=4, n_kv_heads=2, hidden_dim=64,
        dtype=jnp.float32, param_dtype=jnp.float32,
    )
    draft = Llama(draft_cfg)
    dp = draft.init(jax.random.PRNGKey(9), jnp.zeros((1, 8), jnp.int32))["params"]

    cfg = GenerationConfig(max_new_tokens=10, temperature=0.0, prompt_buckets=(16,))
    prompts = [[3, 1, 4, 1, 5], [9, 2]]
    expected = Generator(module, params, cfg)(prompts)
    spec = SpeculativeGenerator(module, params, draft, dp, cfg, gamma=3)
    np.testing.assert_array_equal(spec(prompts), expected)


def test_draft_spec_through_generator_facade():
    """GenerationConfig(draft=DraftSpec(...)) routes the plain Generator façade
    through speculative decoding — same greedy tokens, and stream() yields the
    ragged speculative shape whose totals match __call__."""
    from unionml_tpu.models import DraftSpec, Generator as Gen

    target, tp = _model(0)
    draft, dp = _model(7, n_layers=1, dim=32)
    base = GenerationConfig(max_new_tokens=10, temperature=0.0, prompt_buckets=(16,))
    expected = Generator(target, tp, base)(PROMPTS)

    import dataclasses
    cfg = dataclasses.replace(base, draft=DraftSpec(module=draft, params=dp, gamma=3))
    gen = Gen(target, tp, cfg)
    np.testing.assert_array_equal(gen(PROMPTS), expected)
    assert gen._speculative().rounds >= 1

    # streamed: ragged per-row chunks; concatenated totals equal __call__
    chunks = list(gen.stream(PROMPTS, chunk_size=4))
    totals = [np.concatenate([c[i] for c in chunks]) for i in range(len(PROMPTS))]
    for i, row in enumerate(expected):
        # stream stops emitting a row at its eos/budget; compare the emitted span
        np.testing.assert_array_equal(totals[i], row[: len(totals[i])])
        assert len(totals[i]) == base.max_new_tokens  # no eos configured: full budget


def test_speculative_stream_matches_call_with_eos():
    target, tp = _model(0)
    draft, dp = _model(123, n_layers=1, dim=32)
    free = Generator(
        target, tp, GenerationConfig(max_new_tokens=12, temperature=0.0, prompt_buckets=(16,))
    )(PROMPTS)
    eos = int(free[0][2])
    cfg = GenerationConfig(
        max_new_tokens=12, temperature=0.0, prompt_buckets=(16,), eos_id=eos, pad_id=0
    )
    spec = SpeculativeGenerator(target, tp, draft, dp, cfg, gamma=4)
    called = spec(PROMPTS)
    chunks = list(spec.stream(PROMPTS, chunk_size=3))
    for i in range(len(PROMPTS)):
        total = np.concatenate([c[i] for c in chunks])
        row = called[i]
        hits = np.nonzero(row == eos)[0]
        expected_row = row[: int(hits[0]) + 1] if hits.size else row
        np.testing.assert_array_equal(total, expected_row)


def test_speculative_with_prefix_is_exact():
    """prefix= composes with speculative decoding: both models carry the shared
    prefix in their caches, and greedy output equals the plain Generator run on
    the FULL (prefix + suffix) prompts — through the engine and the façade."""
    import dataclasses

    from unionml_tpu.models import DraftSpec, PrefixCache

    target, tp = _model(0)
    draft, dp = _model(7, n_layers=1, dim=32)
    base = GenerationConfig(max_new_tokens=10, temperature=0.0, prompt_buckets=(8, 16))
    prefix_toks = [5, 11, 2, 9]
    suffixes = [[3, 1, 4], [9, 2, 6, 5], [8]]
    expected = Generator(target, tp, base)([prefix_toks + s for s in suffixes])

    spec = SpeculativeGenerator(target, tp, draft, dp, base, gamma=3)
    prefix = spec._target.cache_prefix(prefix_toks)
    np.testing.assert_array_equal(spec(suffixes, prefix=prefix), expected)
    # memoized draft prefix: a second call must not re-prefill the draft
    built = spec.draft_prefix(prefix)
    assert spec.draft_prefix(prefix) is built

    # façade: config.draft + prefix= in __call__ AND stream
    cfg = dataclasses.replace(base, draft=DraftSpec(module=draft, params=dp, gamma=3))
    gen = Generator(target, tp, cfg)
    fprefix = gen.cache_prefix(prefix_toks)
    np.testing.assert_array_equal(gen(suffixes, prefix=fprefix), expected)
    chunks = list(gen.stream(suffixes, chunk_size=4, prefix=fprefix))
    totals = [np.concatenate([c[i] for c in chunks]) for i in range(len(suffixes))]
    for i, row in enumerate(expected):
        np.testing.assert_array_equal(totals[i], row[: len(totals[i])])

    # a hand-built PrefixCache (no token ids) cannot feed the draft
    bare = PrefixCache(layers=fprefix.layers, length=fprefix.length)
    with pytest.raises(ValueError, match="token ids"):
        gen(suffixes, prefix=bare)
