"""Jitted bucketed predictor tests (SURVEY.md §7 hard part 4).

The contract under test: across requests of varied batch sizes, the number of XLA
traces (== compiles) stays at len(config.buckets()) because every request is padded
to a bucket shape before dispatch; non-jittable predictors fall back to eager with
identical results.
"""

from typing import Any, Dict, List

import numpy as np
import pandas as pd
import pytest

from unionml_tpu import Dataset, Model
from unionml_tpu.serving import CompiledPredictor, ServingConfig, serving_app


def _linear_params():
    return {"w": np.arange(3, dtype=np.float32), "b": np.float32(1.0)}


def _linear_predict(params, feats):
    return feats @ params["w"] + params["b"]


def test_compile_count_stays_at_bucket_count():
    cp = CompiledPredictor(_linear_predict, ServingConfig(bucket_sizes=[4, 8]))
    params = _linear_params()
    for n in (1, 2, 3, 4, 5, 7, 8, 3, 6, 1):
        out = np.asarray(cp(params, np.ones((n, 3), np.float32)))
        assert out.shape == (n,)
    assert cp.traces == 2  # one compile per bucket, none per request size


def test_padded_results_match_unpadded():
    cp = CompiledPredictor(_linear_predict, ServingConfig(bucket_sizes=[8]))
    params = _linear_params()
    feats = np.random.default_rng(0).normal(size=(5, 3)).astype(np.float32)
    np.testing.assert_allclose(np.asarray(cp(params, feats)), _linear_predict(params, feats), rtol=1e-6)


def test_oversized_request_chunks_through_largest_bucket():
    cp = CompiledPredictor(_linear_predict, ServingConfig(bucket_sizes=[4, 8]))
    params = _linear_params()
    feats = np.random.default_rng(1).normal(size=(21, 3)).astype(np.float32)
    out = np.asarray(cp(params, feats))
    assert out.shape == (21,)
    np.testing.assert_allclose(out, _linear_predict(params, feats), rtol=1e-6)
    assert cp.traces == 1  # every chunk (incl. the 5-row remainder) pads to the 8-bucket


def test_warmup_precompiles_all_buckets():
    cfg = ServingConfig(bucket_sizes=[2, 4], feature_shape=(3,))
    cp = CompiledPredictor(_linear_predict, cfg)
    params = _linear_params()
    for bucket in cfg.buckets():
        assert cp.warmup(params, bucket)
    assert cp.traces == 2
    cp(params, np.ones((3, 3), np.float32))
    assert cp.traces == 2  # request-path call hits the warm cache


def test_single_warmup_call_precompiles_every_bucket():
    """The off-bucket cold-compile fix: ONE warmup call (no batch_size) warms
    every configured bucket, so a request landing in a different bucket than
    the warmed one never pays a lazy compile."""
    cfg = ServingConfig(bucket_sizes=[2, 4, 8], feature_shape=(3,))
    cp = CompiledPredictor(_linear_predict, cfg)
    params = _linear_params()
    assert cp.warmup(params)
    assert cp.traces == 3  # every bucket, not just one
    for n in (1, 3, 7):  # each lands in a different bucket
        cp(params, np.ones((n, 3), np.float32))
    assert cp.traces == 3  # nothing compiled lazily on the request path


def test_warmup_with_batch_size_still_covers_off_buckets():
    """A legacy per-bucket warmup call now sweeps the whole set too — the
    regression this PR fixes was exactly a warmed server compiling on the
    first off-bucket request."""
    cfg = ServingConfig(bucket_sizes=[2, 8], feature_shape=(3,))
    cp = CompiledPredictor(_linear_predict, cfg)
    params = _linear_params()
    assert cp.warmup(params, 2)
    assert cp.traces == 2
    cp(params, np.ones((5, 3), np.float32))  # the 8-bucket: already warm
    assert cp.traces == 2


def test_warmup_without_feature_shape_is_skipped():
    cp = CompiledPredictor(_linear_predict, ServingConfig(bucket_sizes=[4]))
    assert cp.warmup(_linear_params(), 4) is False
    assert cp.traces == 0


def test_eager_fallback_for_unjittable_features():
    def predict(params, feats):
        # sklearn-style body: requires a real DataFrame, not a tracer
        return [str(v) for v in feats["label"]]

    cp = CompiledPredictor(predict, ServingConfig(bucket_sizes=[4]))
    feats = pd.DataFrame({"label": ["a", "b"]})
    assert cp(None, feats) == ["a", "b"]
    assert cp._eager
    assert cp.traces == 0


def test_eager_fallback_for_untraceable_predictor():
    def predict(params, feats):
        return [float(x) for x in np.asarray(feats).sum(axis=1)]  # float() breaks tracing

    cp = CompiledPredictor(predict, ServingConfig(bucket_sizes=[4]))
    feats = np.ones((2, 3), np.float32)
    assert cp(None, feats) == [3.0, 3.0]
    assert cp._eager
    # subsequent calls stay eager and keep working
    assert cp(None, feats) == [3.0, 3.0]


def test_mesh_placement_rounds_buckets_to_data_axis():
    from unionml_tpu.parallel.mesh import MeshSpec

    cfg = ServingConfig(bucket_sizes=[3, 6], mesh=MeshSpec(data=4, model=-1))
    cp = CompiledPredictor(_linear_predict, cfg)
    assert cp._buckets() == (4, 8)  # rounded up to multiples of the data axis
    params = _linear_params()
    out = np.asarray(cp(params, np.ones((3, 3), np.float32)))
    assert out.shape == (3,)
    assert cp.traces == 1


@pytest.fixture
def jax_serving_model() -> Model:
    dataset = Dataset(name="lin_data", targets=["y"], test_size=0.2)

    @dataset.reader
    def reader(n: int = 32) -> pd.DataFrame:
        rng = np.random.default_rng(3)
        frame = pd.DataFrame({"x1": rng.normal(size=n), "x2": rng.normal(size=n)})
        frame["y"] = frame["x1"] + frame["x2"]
        return frame

    def init(hyperparameters: Any = None) -> Dict[str, Any]:
        return {"w": np.zeros(2, np.float32)}

    model = Model(name="lin_model", init=init, dataset=dataset)

    @model.trainer
    def trainer(params: Dict[str, Any], features: pd.DataFrame, target: pd.DataFrame) -> Dict[str, Any]:
        w, *_ = np.linalg.lstsq(features.to_numpy(), target.to_numpy().ravel(), rcond=None)
        return {"w": w.astype(np.float32)}

    @model.predictor(config=ServingConfig(bucket_sizes=[4], feature_shape=(2,), max_wait_ms=1.0))
    def predictor(params: Dict[str, Any], features: pd.DataFrame) -> List[float]:
        return features @ params["w"]

    @model.evaluator
    def evaluator(params: Dict[str, Any], features: pd.DataFrame, target: pd.DataFrame) -> float:
        pred = np.asarray(features.to_numpy() @ params["w"])
        return float(np.mean((pred - target.to_numpy().ravel()) ** 2))

    return model


def test_model_routes_predict_through_compiled_path(jax_serving_model):
    jax_serving_model.train()
    cp = jax_serving_model._compiled_predictor
    assert cp is not None
    preds = jax_serving_model.predict(features=pd.DataFrame({"x1": [1.0, 2.0], "x2": [0.5, 0.25]}))
    assert np.asarray(preds).shape == (2,)
    assert cp.traces == 1 and not cp._eager


def test_serving_startup_warms_all_buckets(jax_serving_model):
    import asyncio
    import json

    jax_serving_model.train()
    app = serving_app(jax_serving_model)
    asyncio.run(app.dispatch("GET", "/health"))  # triggers startup + warmup
    cp = jax_serving_model._compiled_predictor
    assert cp.traces == len(ServingConfig(bucket_sizes=[4]).buckets())
    body = json.dumps({"features": [{"x1": 1.0, "x2": 1.0}]}).encode()
    status, payload, _ = asyncio.run(app.dispatch("POST", "/predict", body))
    assert status == 200 and len(payload) == 1
    assert cp.traces == 1  # request hit the warmed executable
