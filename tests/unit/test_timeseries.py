"""Windowed time-series telemetry (observability/timeseries.py): bucket
rotation, clock-skip, empty-window semantics, and the LatencyWindow freshness
+ lock-contention satellites (serving/metrics.py).

Every test drives an injectable fake clock — no sleeps, no flakes.
"""

import threading

import pytest

from unionml_tpu.observability.timeseries import BucketRing, EngineTimeseries
from unionml_tpu.serving.metrics import LatencyWindow


class FakeClock:
    def __init__(self, t: float = 0.0):
        self.t = t

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += dt


# ------------------------------------------------------------------ BucketRing


def test_bucket_ring_windows_and_rates():
    clock = FakeClock()
    ring = BucketRing(width_s=1.0, buckets=10, clock=clock)
    assert ring.count(5.0) == 0 and ring.rate(5.0) == 0.0  # empty window
    ring.add(3)
    clock.advance(1.0)
    ring.add(2)
    assert ring.total() == 5
    assert ring.count(2.0) == 5
    assert ring.count(1.0) == 2  # only the current bucket
    assert ring.rate(2.0) == pytest.approx(2.5)


def test_bucket_ring_rotation_evicts_old_buckets():
    clock = FakeClock()
    ring = BucketRing(width_s=1.0, buckets=4, clock=clock)
    ring.add(10)
    clock.advance(2.0)
    assert ring.count(4.0) == 10  # still inside the window
    clock.advance(3.0)  # bucket 0's slot has been lapped (ring of 4)
    assert ring.count(4.0) == 0
    assert ring.total() == 10  # lifetime total survives rotation


def test_bucket_ring_clock_skip_reads_as_silence():
    clock = FakeClock()
    ring = BucketRing(width_s=1.0, buckets=8, clock=clock)
    ring.add(7)
    clock.advance(100.0)  # a suspended host / stalled thread
    assert ring.count(8.0) == 0  # no stale counts resurface
    ring.add(1)
    assert ring.count(1.0) == 1  # the lapped slot was zeroed before reuse


def test_bucket_ring_window_wider_than_ring_never_double_counts():
    clock = FakeClock()
    ring = BucketRing(width_s=1.0, buckets=4, clock=clock)
    for _ in range(4):
        ring.add(1)
        clock.advance(1.0)
    # window of 100s over a 4-bucket ring: reads the horizon, not 25 laps
    assert ring.count(100.0) <= 4


def test_bucket_ring_clear_and_validation():
    clock = FakeClock()
    ring = BucketRing(width_s=0.5, buckets=4, clock=clock)
    ring.add(5)
    ring.clear()
    assert ring.total() == 0 and ring.count(2.0) == 0
    with pytest.raises(ValueError):
        BucketRing(width_s=0.0)
    with pytest.raises(ValueError):
        BucketRing(buckets=0)
    with pytest.raises(ValueError):
        ring.count(0.0)


# ------------------------------------------------------------ EngineTimeseries


def test_engine_timeseries_rates_snapshot_never_none():
    clock = FakeClock()
    ts = EngineTimeseries(
        clock=clock, horizon_s=30.0,
        ttft=LatencyWindow(clock=clock), tbt=LatencyWindow(clock=clock),
    )
    snap = ts.rates(10.0)
    assert snap["tokens_per_s"] == 0.0 and snap["shed_ratio"] == 0.0
    assert snap["ttft_ms"] == {"window": 0} and snap["tbt_ms"] == {"window": 0}
    assert all(value is not None for value in snap.values())

    ts.tokens.add(40)
    ts.admissions.add(3)
    ts.sheds.add(1)
    ts.ttft.observe(0.050)
    snap = ts.rates(10.0)
    assert snap["tokens_per_s"] == pytest.approx(4.0)
    assert snap["shed_ratio"] == pytest.approx(0.25)
    assert snap["ttft_ms"]["window"] == 1


def test_engine_timeseries_shed_ratio_and_arrivals():
    clock = FakeClock()
    ts = EngineTimeseries(clock=clock, horizon_s=30.0)
    assert ts.shed_ratio(10.0) == 0.0  # no arrivals -> 0, not a ZeroDivision
    ts.admissions.add(8)
    ts.sheds.add(2)
    assert ts.arrivals(10.0) == 10
    assert ts.shed_ratio(10.0) == pytest.approx(0.2)
    clock.advance(15.0)  # everything ages out of the window
    assert ts.shed_ratio(10.0) == 0.0


# ------------------------------------------- LatencyWindow freshness satellite


def test_latency_window_snapshot_reports_freshness_ages():
    clock = FakeClock(100.0)
    win = LatencyWindow(clock=clock)
    win.observe(0.010)
    clock.advance(2.0)
    win.observe(0.030)
    clock.advance(1.0)
    snap = win.snapshot()
    assert snap["window"] == 2
    assert snap["newest_age_ms"] == pytest.approx(1000.0)
    assert snap["oldest_age_ms"] == pytest.approx(3000.0)
    # the {"window": 0} contract is untouched: no ages, no None values
    assert LatencyWindow(clock=clock).snapshot() == {"window": 0}


def test_latency_window_time_decayed_percentiles():
    clock = FakeClock()
    win = LatencyWindow(clock=clock)
    win.observe(1.0)  # an ancient 1000ms sample
    clock.advance(120.0)
    win.observe(0.010)
    win.observe(0.012)
    full = win.snapshot()
    assert full["window"] == 3 and full["max_ms"] == pytest.approx(1000.0)
    recent = win.snapshot(window_s=60.0)
    assert recent["window"] == 2
    assert recent["max_ms"] == pytest.approx(12.0)  # the stale sample decayed out
    # a window no sample survives reports empty, not None gauges
    clock.advance(120.0)
    assert win.snapshot(window_s=60.0) == {"window": 0}


# ----------------------------------------- LatencyWindow contention satellite


def test_latency_window_snapshot_sorts_outside_the_lock():
    """The /metrics-scrape stall regression: sorting the 10k-deep reservoir
    must happen on a copy OUTSIDE the producer lock, so observe() (the token
    emission path) is never blocked behind a scrape. Deterministic probe: the
    sample values record whether the window's lock was held at each sort
    comparison."""
    win = LatencyWindow()
    held = []

    class Probe(float):
        def __lt__(self, other):  # sorted() drives comparisons through this
            held.append(win._lock.locked())
            return float.__lt__(self, other)

    for i in range(64):
        win.observe(Probe(i % 7))
    snap = win.snapshot()
    assert snap["window"] == 64
    assert held, "sort never ran"
    assert not any(held), "snapshot sorted while holding the producer lock"


def test_latency_window_observe_concurrent_with_snapshots():
    """Producers and scrapers hammering one window: no exceptions, sane
    snapshots (the copy-then-sort path is safe under concurrency)."""
    win = LatencyWindow(window=512)
    stop = threading.Event()
    errors = []

    def produce():
        try:
            while not stop.is_set():
                win.observe(0.001)
        except Exception as exc:  # pragma: no cover
            errors.append(exc)

    threads = [threading.Thread(target=produce) for _ in range(2)]
    for t in threads:
        t.start()
    try:
        for _ in range(200):
            snap = win.snapshot()
            assert snap == {"window": 0} or snap["p50_ms"] == pytest.approx(1.0)
    finally:
        stop.set()
        for t in threads:
            t.join()
    assert not errors
