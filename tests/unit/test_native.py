"""Native host runtime: C++ records parser + its serving/dataset fast paths.

The contract under test: the native path NEVER changes semantics — for every
supported payload it must produce the same features/predictions as the Python
path, and for everything else it must return None so the Python path runs.
"""

import asyncio
from pathlib import Path
import json
from typing import List

import numpy as np
import pandas as pd
import pytest
from sklearn.linear_model import LogisticRegression

from unionml_tpu import Dataset, Model
from unionml_tpu.native import native_available, parse_records

pytestmark = pytest.mark.skipif(not native_available(), reason="no native toolchain")


def test_parse_records_values_and_layout():
    matrix, columns, _ = parse_records(
        b'[{"x": 1, "y": 2.5, "flag": true}, {"x": -3e2, "y": null, "flag": false}]'
    )
    assert columns == ["x", "y", "flag"]
    np.testing.assert_allclose(matrix[0], [1.0, 2.5, 1.0])
    assert matrix[1, 0] == -300.0 and np.isnan(matrix[1, 1]) and matrix[1, 2] == 0.0
    assert matrix.dtype == np.float64


def test_parse_records_empty_and_whitespace():
    matrix, columns, _ = parse_records(b'  [ ]  ')
    assert matrix.shape == (0, 0) and columns == []


@pytest.mark.parametrize(
    "payload",
    [
        b'[{"a": "string"}]',      # strings unsupported
        b'[{"a": [1]}]',           # nesting unsupported
        b'[{"a": 1}, {"b": 1}]',   # ragged keys
        b'[{"a": 1, "a": 2}]',     # duplicate keys: json.loads does last-wins
        b'[{"a": 1}, {"a": 1, "b": 2}]',  # column count mismatch
        b'{"a": 1}',               # not an array
        b'[{"a": 1}] trailing',    # trailing garbage in strict mode
        b'',
    ],
)
def test_parse_records_falls_back(payload):
    assert parse_records(payload) is None


def test_parse_records_prefix_mode():
    matrix, columns, consumed = parse_records(b'[{"a": 7}] , "other": 1}', allow_trailing=True)
    assert matrix[0, 0] == 7.0 and columns == ["a"]
    assert b'[{"a": 7}]' == b'[{"a": 7}] , "other": 1}'[:consumed].strip()


def _digits_like_app():
    dataset = Dataset(name="native_ds", targets=["y"], test_size=0.2)
    model = Model(name="native_model", init=LogisticRegression, dataset=dataset)

    @dataset.reader
    def reader(n: int = 80) -> pd.DataFrame:
        rng = np.random.default_rng(3)
        frame = pd.DataFrame({"x1": rng.normal(size=n), "x2": rng.normal(size=n)})
        frame["y"] = (frame["x1"] + frame["x2"] > 0).astype(int)
        return frame

    @model.trainer
    def trainer(est: LogisticRegression, features: pd.DataFrame, target: pd.DataFrame) -> LogisticRegression:
        return est.fit(features, target.squeeze())

    @model.predictor
    def predictor(est: LogisticRegression, features: pd.DataFrame) -> List[float]:
        return [float(x) for x in est.predict(features)]

    @model.evaluator
    def evaluator(est: LogisticRegression, features: pd.DataFrame, target: pd.DataFrame) -> float:
        return float(est.score(features, target.squeeze()))

    return dataset, model


def test_dataset_fast_path_matches_python_path():
    dataset, _ = _digits_like_app()
    records = [{"x1": 0.25, "x2": -1.5, "y": 1}, {"x1": -2.0, "x2": 0.5, "y": 0}]
    payload = json.dumps(records).encode()

    fast = dataset.get_features_from_bytes(payload)
    assert fast is not None
    frame, consumed = fast
    assert consumed == len(payload)
    slow = dataset.get_features(records)
    assert list(frame.columns) == list(slow.columns) == ["x1", "x2"]  # target dropped
    np.testing.assert_allclose(frame.to_numpy(), slow.to_numpy().astype(np.float32))

    # JSON-string features through the default loader also take the native path
    via_loader = dataset.get_features(json.dumps(records))
    np.testing.assert_allclose(via_loader.to_numpy(), slow.to_numpy(), atol=1e-6)


def test_dataset_fast_path_schema_cache_is_correct_and_bounded():
    """The per-column-tuple schema cache (the serving hot-loop win, ~5x on the
    dispatch path) must be invisible: repeated and alternating column sets give
    the same frames as the uncached first call, missing feature columns still
    bail to the Python path, and hostile ragged schemas cannot grow the cache
    unboundedly."""
    dataset, _ = _digits_like_app()
    with_target = json.dumps([{"x1": 1.0, "x2": 2.0, "y": 1}]).encode()
    only_features = json.dumps([{"x1": 3.0, "x2": 4.0}]).encode()

    for _ in range(3):  # alternate: both schemas stay cached and correct
        f1, _ = dataset.get_features_from_bytes(with_target)
        assert list(f1.columns) == ["x1", "x2"] and f1.to_numpy().tolist() == [[1.0, 2.0]]
        f2, _ = dataset.get_features_from_bytes(only_features)
        assert list(f2.columns) == ["x1", "x2"] and f2.to_numpy().tolist() == [[3.0, 4.0]]
    assert len(dataset._native_schema_cache) == 2

    # explicit features list with a column the wire lacks: decline (cached misses
    # must not mask the Python path's error)
    dataset._features = ["x1", "missing"]
    dataset._native_schema_cache.clear()
    assert dataset.get_features_from_bytes(only_features) is None
    dataset._features = []

    # the cache is capped: 100 distinct schemas leave <= 64 entries behind
    dataset._native_schema_cache.clear()
    for i in range(100):
        payload = json.dumps([{f"c{i}": 1.0, "x1": 2.0}]).encode()
        dataset.get_features_from_bytes(payload)
    assert len(dataset._native_schema_cache) <= 64

    # oversized schemas are served but never retained (a 64 MB body can carry
    # ~1M distinct column names; caching it would pin that memory forever)
    dataset._native_schema_cache.clear()
    wide = json.dumps([{f"w{i}": float(i) for i in range(5000)}]).encode()
    out = dataset.get_features_from_bytes(wide)
    assert out is not None and out[0].shape == (1, 5000)
    assert len(dataset._native_schema_cache) == 0


def test_dataset_fast_path_declines_custom_pipeline():
    dataset, _ = _digits_like_app()

    @dataset.feature_loader
    def feature_loader(raw) -> pd.DataFrame:
        return pd.DataFrame(raw) * 2

    assert dataset.get_features_from_bytes(b'[{"x1": 1, "x2": 2}]') is None


def test_serving_fast_path_matches_slow_path():
    dataset, model = _digits_like_app()
    model.train(hyperparameters={"max_iter": 500})
    app = model.serve()

    records = [{"x1": 2.0, "x2": 1.0}, {"x1": -3.0, "x2": -1.0}]
    body = json.dumps({"features": records}).encode()
    fast_features = app._predict_features_fast(body)
    assert fast_features is not None, "flat numeric envelope must take the native path"

    status, fast_out, _ = asyncio.run(app.dispatch("POST", "/predict", body))
    assert status == 200

    # slow path: force the Python route via a payload the parser rejects (string field
    # dropped by get_features through pandas) -> same predictions
    slow_out = model.predict(features=records)
    assert fast_out == slow_out == [1.0, 0.0]

    # an envelope with extra keys must decline the fast path
    assert app._predict_features_fast(json.dumps({"features": records, "inputs": {}}).encode()) is None
    # inputs-only payloads unaffected
    status, out, _ = asyncio.run(app.dispatch("POST", "/predict", json.dumps({"inputs": {"n": 16}}).encode()))
    assert status == 200 and len(out) == 16


def test_parse_records_rejects_non_json_numbers():
    """strtod alone accepts hex/Infinity/leading-plus; the JSON-grammar scanner must
    reject them so native and fallback deployments 400 on the same payloads."""
    for payload in (b'[{"a": 0x1A}]', b'[{"a": Infinity}]', b'[{"a": +1}]', b'[{"a": .5}]', b'[{"a": 01}]'):
        assert parse_records(payload) is None, payload


def test_parse_records_float64_exactness():
    matrix, _, _ = parse_records(b'[{"a": 16777217, "b": 1e300}]')
    assert matrix.dtype == np.float64
    assert matrix[0, 0] == 16777217.0  # would round to 16777216 in float32
    assert matrix[0, 1] == 1e300  # would overflow to inf in float32


def test_parse_records_empty_column_name():
    matrix, columns, _ = parse_records(b'[{"": 1.5}]')
    assert columns == [""] and matrix[0, 0] == 1.5


def test_path_features_are_not_rereresolved(tmp_path):
    """A Path's file contents must be parsed as JSON, never re-resolved as another
    path (regression: the sniffing step applies only to plain strings)."""
    inner = tmp_path / "data.json"
    inner.write_text('[{"x1": 1.0, "x2": 2.0}]')
    outer = tmp_path / "f.txt"
    outer.write_text(str(inner))  # contents are a path string, not JSON

    dataset, _ = _digits_like_app()
    with pytest.raises(json.JSONDecodeError):
        dataset.get_features(Path(str(outer)))
    # but the same string VALUE is sniffed as a path (reference behavior)
    loaded = dataset.get_features(str(inner))
    assert list(loaded.columns) == ["x1", "x2"]


# ---------------------------------------------------------------- fuzzing

hypothesis = pytest.importorskip("hypothesis")  # not in the CI install set
from hypothesis import given, settings, strategies as st  # noqa: E402

_ident = st.text(
    alphabet=st.characters(min_codepoint=32, max_codepoint=126, exclude_characters='"\\'),
    min_size=0,
    max_size=12,
)
_value = st.one_of(
    st.integers(min_value=-(10**12), max_value=10**12),
    st.floats(allow_nan=False, allow_infinity=False, width=64),
    st.booleans(),
    st.none(),
)


@settings(max_examples=200, deadline=None)
@given(
    columns=st.lists(_ident, min_size=1, max_size=6, unique=True),
    n_rows=st.integers(min_value=1, max_value=8),
    data=st.data(),
)
def test_parse_records_fuzz_matches_json_loads(columns, n_rows, data):
    """Every generated payload is inside the parser's supported subset (flat
    records, JSON-grammar numbers, escape-free keys), so it MUST take the fast
    path and agree with the Python path on shape, column order, and values
    (NaN for null, 1/0 for bools)."""
    rows = [
        {c: data.draw(_value, label=f"row{i}[{c}]") for c in columns}
        for i in range(n_rows)
    ]
    payload = json.dumps(rows).encode()
    result = parse_records(payload)
    assert result is not None, f"well-formed flat records must take the fast path: {payload[:120]!r}"
    matrix, names, consumed = result
    assert names == columns and matrix.shape == (n_rows, len(columns))
    assert consumed == len(payload)
    for i, row in enumerate(rows):
        for j, c in enumerate(columns):
            expected = row[c]
            got = matrix[i, j]
            if expected is None:
                assert np.isnan(got)
            elif isinstance(expected, bool):
                assert got == (1.0 if expected else 0.0)
            else:
                assert got == float(expected), (expected, got)


@settings(max_examples=150, deadline=None)
@given(junk=st.binary(min_size=0, max_size=80))
def test_parse_records_fuzz_never_crashes_on_garbage(junk):
    """Arbitrary bytes must produce None or a valid matrix — never a crash
    (the parser runs in-process on untrusted request bodies)."""
    result = parse_records(junk)
    if result is not None:
        matrix, names, consumed = result
        assert matrix.shape[1] == len(names)
        assert 0 <= consumed <= len(junk)
