"""Dataset pipeline tests — mirrors reference tests/unit/test_dataset.py coverage."""

import json
from typing import Dict, List, Optional, Tuple

import numpy as np
import pandas as pd
import pytest

from unionml_tpu import Dataset, ExecutionGraph
from unionml_tpu.dataset import ReaderReturnTypeSource


def test_reader_registration_and_stage(simple_dataset: Dataset):
    stage = simple_dataset.dataset_task()
    assert stage.name == "test_dataset.dataset_task"
    assert "sample_frac" in stage.interface.inputs
    assert list(stage.interface.outputs) == ["data"]
    data = stage(sample_frac=1.0, random_state=0)
    assert isinstance(data, pd.DataFrame)
    assert len(data) == 100


def test_reader_requires_return_annotation():
    dataset = Dataset(name="d")
    with pytest.raises(TypeError, match="return annotation cannot be empty"):

        @dataset.reader
        def reader():
            return pd.DataFrame()


def test_get_data_default_pipeline(simple_dataset: Dataset):
    raw = simple_dataset.dataset_task()(sample_frac=1.0, random_state=0)
    data = simple_dataset.get_data(raw)
    assert set(data) == {"train", "test"}
    X_train, y_train = data["train"]
    X_test, y_test = data["test"]
    assert list(X_train.columns) == ["x1", "x2"]
    assert list(y_train.columns) == ["y"]
    assert len(X_train) == 80 and len(X_test) == 20
    # splits are disjoint
    assert not set(X_train.index) & set(X_test.index)


def test_get_data_splitter_kwargs_override(simple_dataset: Dataset):
    raw = simple_dataset.dataset_task()(sample_frac=1.0, random_state=0)
    data = simple_dataset.get_data(raw, splitter_kwargs={"test_size": 0.5})
    assert len(data["train"][0]) == 50


def test_get_features_from_records(simple_dataset: Dataset):
    features = simple_dataset.get_features([{"x1": 0.1, "x2": -0.2}, {"x1": 1.0, "x2": 2.0}])
    assert isinstance(features, pd.DataFrame)
    assert list(features.columns) == ["x1", "x2"]
    assert len(features) == 2


def test_get_features_from_json_file(simple_dataset: Dataset, tmp_path):
    path = tmp_path / "features.json"
    path.write_text(json.dumps([{"x1": 0.5, "x2": 0.5}]))
    features = simple_dataset.get_features(path)
    assert len(features) == 1


def test_custom_loader_overrides_datatype():
    dataset = Dataset(name="d", targets=["y"])

    @dataset.reader
    def reader() -> str:
        return json.dumps([{"x": 1, "y": 0}, {"x": 2, "y": 1}])

    assert dataset.dataset_datatype_source is ReaderReturnTypeSource.READER

    @dataset.loader
    def loader(data: str) -> pd.DataFrame:
        return pd.DataFrame(json.loads(data))

    assert dataset.dataset_datatype_source is ReaderReturnTypeSource.LOADER
    assert dataset.dataset_datatype["data"] is pd.DataFrame
    data = dataset.get_data(reader())
    assert isinstance(data["train"][0], pd.DataFrame)


def test_custom_splitter_and_parser_on_list_data():
    dataset = Dataset(name="d")

    @dataset.reader
    def reader() -> List[Dict]:
        return [{"x": i, "y": i % 2} for i in range(10)]

    @dataset.splitter
    def splitter(data: List[Dict], test_size: float, shuffle: bool, random_state: int) -> Tuple[List[Dict], List[Dict]]:
        n_test = int(len(data) * test_size)
        return data[:-n_test], data[-n_test:]

    @dataset.parser
    def parser(data: List[Dict], features: Optional[List[str]], targets: List[str]) -> Tuple[List[Dict], List[Dict]]:
        return (
            [{k: v for k, v in row.items() if k != "y"} for row in data],
            [{"y": row["y"]} for row in data],
        )

    data = dataset.get_data(reader())
    assert len(data["train"][0]) == 8
    assert len(data["test"][0]) == 2
    assert "y" not in data["train"][0][0]


def test_kwargs_dataclass_synthesis(simple_dataset: Dataset):
    splitter_kwargs = simple_dataset.splitter_kwargs_type()
    assert splitter_kwargs.test_size == 0.2
    assert splitter_kwargs.shuffle is True
    assert splitter_kwargs.random_state == 12345
    # round-trips through json
    assert type(splitter_kwargs).from_json(splitter_kwargs.to_json()) == splitter_kwargs

    parser_kwargs = simple_dataset.parser_kwargs_type()
    assert parser_kwargs.targets == ["y"]


def test_dataset_stage_in_custom_graph(simple_dataset: Dataset):
    """Stages compose into hand-written graphs (reference test_dataset.py:129-145)."""
    graph = ExecutionGraph("custom")
    graph.add_input("sample_frac", float)
    graph.add_input("random_state", int)
    node = graph.add_node(
        simple_dataset.dataset_task(),
        sample_frac=graph.inputs["sample_frac"],
        random_state=graph.inputs["random_state"],
    )
    graph.add_output("data", node.outputs["data"])
    out = graph(sample_frac=1.0, random_state=0)
    assert isinstance(out, pd.DataFrame)


def test_from_sqlite_query(tmp_path):
    import sqlite3

    db = tmp_path / "test.db"
    with sqlite3.connect(db) as conn:
        conn.execute("CREATE TABLE points (x1 REAL, x2 REAL, y INTEGER)")
        rng = np.random.default_rng(3)
        rows = [(float(a), float(b), int(a + b > 0)) for a, b in rng.normal(size=(50, 2))]
        conn.executemany("INSERT INTO points VALUES (?, ?, ?)", rows)

    dataset = Dataset.from_sqlite_query(str(db), "SELECT * FROM points", name="sql_dataset", targets=["y"])
    raw = dataset.dataset_task()()
    assert isinstance(raw, pd.DataFrame)
    data = dataset.get_data(raw)
    assert len(data["train"][0]) == 40


def test_iterator_prefetch(simple_dataset: Dataset):
    raw = simple_dataset.dataset_task()(sample_frac=1.0, random_state=0)
    data = simple_dataset.get_data(raw)
    batches = list(simple_dataset.iterator(data["train"], batch_size=16))
    assert len(batches) == 5  # 80 // 16
    X, y = batches[0]
    assert X.shape == (16, 2)
    assert y.shape == (16, 1)


def test_feature_transformer():
    dataset = Dataset(name="d", targets=["y"])

    @dataset.reader
    def reader() -> pd.DataFrame:
        return pd.DataFrame({"x": [1.0, 2.0], "y": [0, 1]})

    @dataset.feature_transformer
    def feature_transformer(features: pd.DataFrame) -> pd.DataFrame:
        return features * 2

    features = dataset.get_features([{"x": 1.0}])
    assert features["x"].iloc[0] == 2.0


def test_from_sqlalchemy_query_gate():
    """SQLAlchemy integration: functional when installed, informative gate when not."""
    try:
        import sqlalchemy  # noqa: F401
    except ImportError:
        with pytest.raises(ImportError, match="requires sqlalchemy"):
            Dataset.from_sqlalchemy_query("sqlite:///x.db", "SELECT 1", name="sa_dataset")
        return
    import sqlite3
    import tempfile

    with tempfile.TemporaryDirectory() as tmp:
        db = f"{tmp}/points.db"
        conn = sqlite3.connect(db)
        conn.execute("CREATE TABLE points (x1 REAL, x2 REAL, y INTEGER)")
        conn.executemany("INSERT INTO points VALUES (?, ?, ?)", [(i, -i, i % 2) for i in range(20)])
        conn.commit()
        conn.close()
        dataset = Dataset.from_sqlalchemy_query(
            f"sqlite:///{db}", "SELECT * FROM points", name="sa_dataset", targets=["y"]
        )
        frame = dataset._reader()
        assert list(frame.columns) == ["x1", "x2", "y"] and len(frame) == 20
