"""Quantized serving end-to-end: int8 weights + int8 KV through the stack.

Rings: (1) the serve-time env readers (``UNIONML_TPU_QUANTIZE`` /
``UNIONML_TPU_KV_CACHE_DTYPE``) — warn-and-fall-back on garbage, never a crash
at app-import time — and their resolution inside ``Generator``; (2) the
continuous engine over an int8 paged pool composed with the radix prefix
cache — warm (cache-hit) output must be BIT-IDENTICAL to a cold int8 prefill
(the same pinned contract PR 6 holds for fp pools); (3) replica and
speculative composition — a pre-quantized Generator replicates bit-identically
and a ``DraftSpec(quantize="int8")`` draft leaves greedy output token-exact.
"""

import dataclasses

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from unionml_tpu.defaults import (
    SERVE_KV_CACHE_DTYPE_ENV_VAR,
    SERVE_QUANTIZE_ENV_VAR,
    serve_kv_cache_dtype,
    serve_quantize,
)
from unionml_tpu.models import GenerationConfig, Generator, Llama, LlamaConfig
from unionml_tpu.ops.quant import QuantizedTensor
from unionml_tpu.serving import ContinuousBatcher, ReplicaSet


@pytest.fixture(scope="module")
def quantizable_gen():
    """A tiny Llama whose MLP kernels (64 x 1024 = 65536 elements) cross
    ``quantize_params``' default ``min_size``, so quantize="int8" really stores
    int8 weights — not a silent no-op."""
    config = LlamaConfig.tiny(
        vocab_size=97, dim=64, n_layers=2, n_heads=4, n_kv_heads=2, hidden_dim=1024,
        dtype=jnp.float32, param_dtype=jnp.float32,
    )
    module = Llama(config)
    params = module.init(jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))["params"]
    return module, params


def _has_quantized_leaf(tree) -> bool:
    return any(
        isinstance(leaf, QuantizedTensor)
        for leaf in jax.tree_util.tree_leaves(tree, is_leaf=lambda x: isinstance(x, QuantizedTensor))
    )


def _drain(stream):
    return [int(t) for chunk in stream for t in np.asarray(chunk).ravel()]


def _cfg(**overrides):
    base = dict(max_new_tokens=10, temperature=0.0, prompt_buckets=(32,))
    base.update(overrides)
    return GenerationConfig(**base)


# ------------------------------------------------------------------ env readers


def test_env_readers_tolerate_garbage_and_accept_modes(monkeypatch, caplog):
    from unionml_tpu._logging import logger

    monkeypatch.setattr(logger, "propagate", True)  # let caplog see records
    for var, reader in (
        (SERVE_QUANTIZE_ENV_VAR, serve_quantize),
        (SERVE_KV_CACHE_DTYPE_ENV_VAR, serve_kv_cache_dtype),
    ):
        monkeypatch.delenv(var, raising=False)
        assert reader() is None
        monkeypatch.setenv(var, "int8")
        assert reader() == "int8"
        monkeypatch.setenv(var, " INT8 ")  # normalized, deployment-env friendly
        assert reader() == "int8"
        for off in ("none", "off", "0", ""):
            monkeypatch.setenv(var, off)
            assert reader() is None
        with caplog.at_level("WARNING", logger="unionml_tpu"):
            monkeypatch.setenv(var, "fp4")
            assert reader() is None  # warned, not crashed
        assert any("fp4" in record.message for record in caplog.records)
        caplog.clear()
        monkeypatch.delenv(var, raising=False)


def test_generator_resolves_serve_env_and_validates(quantizable_gen, monkeypatch):
    module, params = quantizable_gen
    monkeypatch.setenv(SERVE_QUANTIZE_ENV_VAR, "int8")
    monkeypatch.setenv(SERVE_KV_CACHE_DTYPE_ENV_VAR, "int8")
    gen = Generator(module, params, _cfg())
    assert gen.quantize == "int8" and gen.config.kv_cache_dtype == "int8"
    assert _has_quantized_leaf(gen.params)
    # garbage degrades to full precision at construction, never crashes
    monkeypatch.setenv(SERVE_QUANTIZE_ENV_VAR, "fp4")
    monkeypatch.setenv(SERVE_KV_CACHE_DTYPE_ENV_VAR, "garbage")
    fallback = Generator(module, params, _cfg())
    assert fallback.quantize is None and fallback.config.kv_cache_dtype is None
    assert not _has_quantized_leaf(fallback.params)
    # "none" explicitly overrides an inherited fleet-wide export
    monkeypatch.setenv(SERVE_QUANTIZE_ENV_VAR, "none")
    assert Generator(module, params, _cfg()).quantize is None
    # explicit API misuse still raises the Generator/init_cache ValueError text
    monkeypatch.delenv(SERVE_QUANTIZE_ENV_VAR, raising=False)
    monkeypatch.delenv(SERVE_KV_CACHE_DTYPE_ENV_VAR, raising=False)
    with pytest.raises(ValueError, match="unsupported kv_cache_dtype"):
        Generator(module, params, _cfg(kv_cache_dtype="fp8"))
    with pytest.raises(ValueError, match="unsupported quantize mode"):
        Generator(module, params, _cfg(), quantize="fp4")


# ------------------------------------------------------ engine x prefix cache


PROMPTS_SHARED = [list(range(1, 21)) + [70 + i] for i in range(4)]


def test_int8_pool_warm_equals_cold_equals_sequential(quantizable_gen):
    """The acceptance contract: with int8 weights AND an int8 paged pool, a
    radix-cache-hit admission (scales gathered alongside the int8 values)
    yields streams bit-identical to the cold int8 prefill and to a sequential
    quantized Generator run."""
    module, params = quantizable_gen
    cfg = _cfg(kv_cache_dtype="int8")
    sequential = Generator(module, params, cfg, quantize="int8")
    expected = [list(sequential([p])[0]) for p in PROMPTS_SHARED]

    batcher = ContinuousBatcher(
        Generator(module, params, cfg, quantize="int8"), slots=2, decode_chunk=4,
        block_size=8, admit_chunk=8, prefix_cache=True,
    )
    try:
        results = [_drain(batcher.submit(p)) for p in PROMPTS_SHARED]
        assert results == expected
        stats = batcher.stats()
        assert stats["prefix_cache"]["hits"] == len(PROMPTS_SHARED) - 1
        # decode-side insertion publishes the first stream's prompt+generated
        # run, so later prompts match their WHOLE 20-token shared prefix (the
        # partial third block rides CoW), not just the 2 fully-shared blocks
        assert stats["prefix_cache"]["tokens_avoided"] == 20 * (len(PROMPTS_SHARED) - 1)
        # the pool really is int8 (values) + f32 (scale planes)
        pool = batcher._carry[0]
        assert pool[0]["k"].dtype == jnp.int8
        assert pool[0]["k_scale"].dtype == jnp.float32
        # int8-aware byte gauges on the same live engine, never None:
        # head_dim 16 at int8 -> 2 layers * 2 kv heads * 8 positions * (2*16+8)
        kv = stats["kv_blocks"]
        assert kv["kv_dtype"] == "int8"
        assert kv["block_bytes"] == 2 * 2 * 8 * (2 * 16 + 8)
        assert kv["used_bytes"] == kv["used"] * kv["block_bytes"]
        pc = stats["prefix_cache"]
        assert pc["cached_bytes"] == pc["cached_blocks"] * kv["block_bytes"]
        assert pc["cached_bytes"] > 0
        assert all(value is not None for value in kv.values())
        assert all(value is not None for value in pc.values())
        # an fp pool reports its own dtype and the wider per-block bytes
        # (construction-time gauges only: no stream, no extra compiles)
        fp = ContinuousBatcher(Generator(module, params, _cfg()), slots=1, block_size=8)
        fp_kv = fp.stats()["kv_blocks"]
        fp.close()
        assert fp_kv["kv_dtype"] == "float32"
        assert fp_kv["block_bytes"] == 2 * 2 * 8 * (2 * 16 * 4)
    finally:
        batcher.close()


@pytest.mark.slow  # ~7s; tier-1 keeps the warm==cold==sequential identity test
# above — this adds the mid-block CoW leg, which the fp ring also pins daily
def test_int8_pool_cow_divergence_stays_exact(quantizable_gen):
    """Mid-block divergence over an int8 pool: the partially shared tail block
    copy-on-writes through the gather+scatter with its scale planes riding
    along, and the stream stays bit-identical to the cold run."""
    module, params = quantizable_gen
    cfg = _cfg(kv_cache_dtype="int8")
    long_a = list(range(1, 28))
    long_b = list(range(1, 21)) + [90, 91, 92]  # shares 20 tokens: mid-block
    sequential = Generator(module, params, cfg, quantize="int8")
    expected = [list(sequential([p])[0]) for p in (long_a, long_b)]

    batcher = ContinuousBatcher(
        Generator(module, params, cfg, quantize="int8"), slots=2, decode_chunk=3,
        block_size=8, prefix_cache=True,
    )
    try:
        results = [_drain(batcher.submit(p)) for p in (long_a, long_b)]
        assert results == expected
        stats = batcher.stats()["prefix_cache"]
        assert stats["cow_copies"] == 1 and stats["tokens_avoided"] == 20
    finally:
        batcher.close()


# ------------------------------------------------------------ replicas + draft


@pytest.mark.slow  # ~7s; the emulated dp=2 x tp=2 ring pins the same
# from_generator dequantize-requantize path in tier-1 at mesh scale
def test_pre_quantized_generator_replicates_bit_identically(quantizable_gen):
    """The path replicas.py used to reject: a quantized Generator replicates by
    dequantize-then-requantize per placement — an exact round trip, so the
    fleet's streams equal the original engine's token for token."""
    module, params = quantizable_gen
    gen = Generator(module, params, _cfg(kv_cache_dtype="int8"), quantize="int8")
    expected = [list(gen([p])[0]) for p in PROMPTS_SHARED[:3]]
    rs = ReplicaSet.from_generator(gen, replicas=2, slots=2, decode_chunk=4)
    try:
        assert rs.replicas == 2
        for engine in rs.batchers:
            assert engine.gen.quantize == "int8"
            assert engine.gen.config.kv_cache_dtype == "int8"
            assert _has_quantized_leaf(engine.gen.params)
        results = [_drain(rs.submit(p)) for p in PROMPTS_SHARED[:3]]
        assert results == expected
    finally:
        rs.close()


@pytest.mark.slow  # ~7s; greedy draft-invariance is structural (the draft only
# proposes) and the speculative ring already pins it for the fp draft in tier-1
def test_quantized_draft_spec_keeps_greedy_exact(quantizable_gen):
    """DraftSpec(quantize="int8"): the draft stores int8 weights (the option
    speculative.py hardcoded away) and greedy output stays token-for-token the
    plain target's — the draft only proposes, the target decides."""
    from unionml_tpu.models import DraftSpec

    module, params = quantizable_gen
    config = module.config
    draft_module = Llama(dataclasses.replace(config, n_layers=1))
    draft_params = draft_module.init(jax.random.PRNGKey(1), jnp.zeros((1, 8), jnp.int32))["params"]
    plain = Generator(module, params, _cfg())
    expected = plain(PROMPTS_SHARED[:2])
    spec_cfg = _cfg(
        draft=DraftSpec(module=draft_module, params=draft_params, gamma=2, quantize="int8")
    )
    gen = Generator(module, params, spec_cfg)
    assert _has_quantized_leaf(gen._speculative()._draft.params)
    np.testing.assert_array_equal(gen(PROMPTS_SHARED[:2]), expected)
    # default (quantize=None, no env): the draft still runs full precision
    spec_plain = _cfg(draft=DraftSpec(module=draft_module, params=draft_params, gamma=2))
    assert not _has_quantized_leaf(
        Generator(module, params, spec_plain)._speculative()._draft.params
    )


# ------------------------------------------------------------------ app + CLI


def test_serving_app_configure_quantization(sklearn_model, monkeypatch):
    from unionml_tpu.serving.app import ServingApp

    monkeypatch.delenv(SERVE_QUANTIZE_ENV_VAR, raising=False)
    monkeypatch.delenv(SERVE_KV_CACHE_DTYPE_ENV_VAR, raising=False)
    app = ServingApp(sklearn_model)
    assert app.quantize is None and app.kv_cache_dtype is None
    app.configure_quantization(quantize="int8", kv_cache_dtype="int8")
    assert app.quantize == "int8" and app.kv_cache_dtype == "int8"
    import os

    assert os.environ[SERVE_QUANTIZE_ENV_VAR] == "int8"
    assert os.environ[SERVE_KV_CACHE_DTYPE_ENV_VAR] == "int8"
    app.configure_quantization(quantize="none")
    assert app.quantize is None and os.environ[SERVE_QUANTIZE_ENV_VAR] == "none"
    with pytest.raises(ValueError, match="unsupported quantize mode"):
        app.configure_quantization(quantize="fp4")
    monkeypatch.delenv(SERVE_QUANTIZE_ENV_VAR, raising=False)
    monkeypatch.delenv(SERVE_KV_CACHE_DTYPE_ENV_VAR, raising=False)


def test_serve_cli_exports_quantize_env_before_app_import(monkeypatch):
    """The --dp-replicas early-export contract: serve writes the env vars
    BEFORE locating the app module, so Generators built at import time resolve
    them; the bogus app ref fails afterwards, proving the ordering."""
    import os

    from click.testing import CliRunner

    from unionml_tpu.cli import app as cli_app

    # register restore-to-absent with monkeypatch before the CLI overwrites
    monkeypatch.delenv(SERVE_QUANTIZE_ENV_VAR, raising=False)
    monkeypatch.delenv(SERVE_KV_CACHE_DTYPE_ENV_VAR, raising=False)
    monkeypatch.setenv(SERVE_QUANTIZE_ENV_VAR, "placeholder")
    monkeypatch.setenv(SERVE_KV_CACHE_DTYPE_ENV_VAR, "placeholder")
    result = CliRunner().invoke(
        cli_app,
        ["serve", "definitely_not_a_module:model", "--quantize", "int8",
         "--kv-cache-dtype", "int8"],
    )
    assert result.exit_code != 0  # the bogus app ref fails AFTER the export
    assert os.environ[SERVE_QUANTIZE_ENV_VAR] == "int8"
    assert os.environ[SERVE_KV_CACHE_DTYPE_ENV_VAR] == "int8"
