"""Observability surface: request ids, traces, the flight recorder, Prometheus
exposition, the /debug endpoints, the profiler hook, and structured logging.

Contracts pinned here (docs/observability.md):

- the request id flows HTTP -> engine -> response and is echoed on EVERY
  response, including 404s, sheds (429/503), and streams;
- with tracing off the hot path allocates no RequestTrace at all (the
  zero-cost-off claim the bench lane regression-tracks);
- flight-recorder eviction, in-flight -> completed transitions, and the
  /debug/requests filters;
- Prometheus rendering escapes labels and never emits a None-valued series;
- the profiler endpoint rejects overlapping captures (409).
"""

import asyncio
import json
import logging
import os
import re
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from unionml_tpu._logging import JsonFormatter, set_log_format
from unionml_tpu.observability import (
    FlightRecorder,
    Tracer,
    render_prometheus,
)
from unionml_tpu.observability import trace as trace_mod
from unionml_tpu.observability.trace import (
    RequestTrace,
    new_request_id,
    sanitize_request_id,
)
from unionml_tpu.serving.http import HTTPServer
from unionml_tpu.serving.metrics import ServingMetrics
from unionml_tpu.serving.overload import QueueFullError


def _server(enabled=True, capacity=8):
    srv = HTTPServer()
    recorder = FlightRecorder(capacity)
    srv.tracer = Tracer(enabled=enabled, recorder=recorder)
    return srv, recorder


def _dispatch(srv, method, path, body=b"", headers=None):
    return asyncio.run(srv.dispatch_with_headers(method, path, body, headers))


async def _ok(body):
    return 200, {"ok": True}, "application/json"


# ------------------------------------------------------------------ request ids


def test_sanitize_request_id_strips_header_injection():
    assert sanitize_request_id("abc\r\nX-Evil: 1") == "abcX-Evil1"
    assert sanitize_request_id("ok-id_1.2") == "ok-id_1.2"
    assert sanitize_request_id("\r\n") is None
    assert sanitize_request_id("") is None
    assert sanitize_request_id(None) is None
    assert len(sanitize_request_id("x" * 500)) == 128


def test_inbound_request_id_honored_and_echoed():
    srv, recorder = _server()
    srv.route("GET", "/x", _ok)
    status, _, _, extra = _dispatch(srv, "GET", "/x", headers={"x-request-id": "req-42"})
    assert status == 200
    assert extra["X-Request-Id"] == "req-42"
    assert recorder.get("req-42")["status"] == 200


def test_generated_request_id_when_header_missing():
    srv, _ = _server(enabled=False)
    srv.route("GET", "/x", _ok)
    _, _, _, extra = _dispatch(srv, "GET", "/x")
    assert re.fullmatch(r"[0-9a-f]{32}", extra["X-Request-Id"])


def test_request_id_echoed_on_404_and_405():
    srv, _ = _server(enabled=False)
    srv.route("GET", "/x", _ok)
    status, _, _, extra = _dispatch(srv, "GET", "/nope", headers={"x-request-id": "a1"})
    assert (status, extra["X-Request-Id"]) == (404, "a1")
    status, _, _, extra = _dispatch(srv, "POST", "/x", headers={"x-request-id": "a2"})
    assert (status, extra["X-Request-Id"]) == (405, "a2")


def test_request_id_echoed_on_shed_paths():
    """429 (inflight cap / queue full) and 503 (draining) must still echo the
    id — correlating a shed with its client is the whole point."""
    srv, recorder = _server()
    srv.route("GET", "/x", _ok)

    async def full(body):
        raise QueueFullError("downstream queue full")

    srv.route("POST", "/full", full)

    srv.max_inflight = 0
    status, _, _, extra = _dispatch(srv, "GET", "/x", headers={"x-request-id": "shed-1"})
    assert (status, extra["X-Request-Id"]) == (429, "shed-1")
    assert "Retry-After" in extra
    srv.max_inflight = None

    status, _, _, extra = _dispatch(srv, "POST", "/full", headers={"x-request-id": "shed-2"})
    assert (status, extra["X-Request-Id"]) == (429, "shed-2")

    srv.draining = True
    status, _, _, extra = _dispatch(srv, "GET", "/x", headers={"x-request-id": "shed-3"})
    assert (status, extra["X-Request-Id"]) == (503, "shed-3")

    # the sheds were traced, with the reason on the timeline
    for rid, reason in (("shed-1", "inflight_cap"), ("shed-2", "queue_full"), ("shed-3", "draining")):
        snap = recorder.get(rid)
        assert {"event": "http.shed", "reason": reason}.items() <= snap["events"][-1].items()


# ------------------------------------------------------------------ zero-cost off


def test_trace_off_allocates_no_request_traces(monkeypatch):
    """With tracing disabled no RequestTrace is ever constructed — not merely
    unused: the constructor is poisoned and dispatch must still succeed."""

    def boom(self, *a, **k):
        raise AssertionError("RequestTrace allocated with tracing off")

    monkeypatch.setattr(RequestTrace, "__init__", boom)
    srv, recorder = _server(enabled=False)
    srv.route("GET", "/x", _ok)
    status, _, _, extra = _dispatch(srv, "GET", "/x")
    assert status == 200
    assert extra["X-Request-Id"]  # ids still flow — only the timeline is off
    assert len(recorder) == 0 and recorder.inflight_count == 0


def test_engine_sessions_carry_no_trace_when_off():
    from unionml_tpu.serving.continuous import _Session

    assert _Session.__dataclass_fields__["trace"].default is None
    assert trace_mod.current_trace() is None  # no ambient trace outside dispatch


# ------------------------------------------------------------------ trace timelines


def test_trace_events_monotonic_nondecreasing_across_threads():
    trace = RequestTrace("rid", "GET", "/x")
    barrier = threading.Barrier(4)

    def worker(i):
        barrier.wait()
        for j in range(50):
            trace.event("tick", worker=i, j=j)

    threads = [threading.Thread(target=worker, args=(i,)) for i in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    offsets = [e["t_ms"] for e in trace.snapshot()["events"]]
    assert offsets == sorted(offsets)
    assert len(offsets) == 200


def test_trace_event_cap_counts_drops():
    trace = RequestTrace("rid", "GET", "/x")
    for i in range(trace_mod._MAX_EVENTS + 7):
        trace.event("e", i=i)
    snap = trace.snapshot()
    assert len(snap["events"]) == trace_mod._MAX_EVENTS
    assert snap["dropped_events"] == 7


def test_trace_finish_idempotent_first_wins():
    trace = RequestTrace("rid", "GET", "/x")
    trace.finish(200)
    trace.finish(500, "late abort")
    assert trace.status == 200 and trace.detail is None


def test_span_context_manager_records_duration():
    trace = RequestTrace("rid", "GET", "/x")
    with trace.span("work", tokens=3):
        time.sleep(0.01)
    (event,) = trace.snapshot()["events"]
    assert event["event"] == "work" and event["tokens"] == 3
    assert event["dur_ms"] >= 9.0


def test_streaming_response_trace_finishes_at_stream_end():
    srv, recorder = _server()

    async def stream(body):
        async def gen():
            yield b"a"
            yield b"bb"

        return 200, gen(), "application/octet-stream"

    srv.route("GET", "/s", stream)

    async def scenario():
        status, payload, _, extra = await srv.dispatch_with_headers(
            "GET", "/s", b"", {"x-request-id": "stream-1"}
        )
        assert recorder.get("stream-1")["in_flight"]  # handler returned, stream open
        chunks = [c async for c in payload]
        return status, chunks

    status, chunks = asyncio.run(scenario())
    assert (status, chunks) == (200, [b"a", b"bb"])
    snap = recorder.get("stream-1")
    assert not snap["in_flight"] and snap["status"] == 200
    sizes = [e["bytes"] for e in snap["events"] if e["event"] == "http.stream_chunk"]
    assert sizes == [1, 2]


# ------------------------------------------------------------------ flight recorder


def _finished_trace(rid, status=200, path="/x"):
    trace = RequestTrace(rid, "GET", path)
    trace.finish(status)
    return trace


def test_flight_recorder_inflight_to_completed_transition():
    recorder = FlightRecorder(4)
    trace = RequestTrace("r1", "GET", "/x")
    recorder.start(trace)
    assert recorder.inflight_count == 1 and len(recorder) == 0
    assert recorder.get("r1")["in_flight"]
    trace.finish(200)
    recorder.complete(trace)
    assert recorder.inflight_count == 0 and len(recorder) == 1
    assert recorder.get("r1")["in_flight"] is False


def test_flight_recorder_evicts_oldest_beyond_capacity():
    recorder = FlightRecorder(3)
    for i in range(5):
        recorder.complete(_finished_trace(f"r{i}"))
    assert len(recorder) == 3
    snap = recorder.snapshot()
    assert [s["request_id"] for s in snap["completed"]] == ["r4", "r3", "r2"]
    assert recorder.get("r0") is None  # evicted


def test_flight_recorder_get_prefers_live_then_newest():
    recorder = FlightRecorder(4)
    recorder.complete(_finished_trace("dup", status=500))
    recorder.complete(_finished_trace("dup", status=200))
    assert recorder.get("dup")["status"] == 200  # newest completed wins
    live = RequestTrace("dup", "GET", "/x")
    recorder.start(live)
    assert recorder.get("dup")["in_flight"]  # the live view wins over the ring


def test_flight_recorder_snapshot_filters_route_status_limit():
    recorder = FlightRecorder(8)
    recorder.complete(_finished_trace("a", status=200, path="/predict"))
    recorder.complete(_finished_trace("b", status=503, path="/predict"))
    recorder.complete(_finished_trace("c", status=200, path="/health"))
    by_route = recorder.snapshot(route="/predict")
    assert {s["request_id"] for s in by_route["completed"]} == {"a", "b"}
    by_status = recorder.snapshot(status=503)
    assert [s["request_id"] for s in by_status["completed"]] == ["b"]
    both = recorder.snapshot(route="/predict", status=200)
    assert [s["request_id"] for s in both["completed"]] == ["a"]
    limited = recorder.snapshot(limit=1)
    assert len(limited["completed"]) == 1


def test_flight_recorder_dump_writes_timelines_to_log():
    # the package logger has propagate=False, so capture with our own handler
    from unionml_tpu._logging import logger

    records = []

    class Capture(logging.Handler):
        def emit(self, record):
            records.append(record.getMessage())

    handler = Capture(level=logging.WARNING)
    logger.addHandler(handler)
    try:
        recorder = FlightRecorder(4)
        recorder.complete(_finished_trace("dumped"))
        recorder.dump("unit test")
    finally:
        logger.removeHandler(handler)
    text = "\n".join(records)
    assert "unit test" in text and "dumped" in text


# ------------------------------------------------------------------ prometheus

#: the text-exposition grammar: a sample line is name{labels} value, where the
#: value is a float/int literal (Prometheus also allows +Inf/-Inf/NaN)
_SAMPLE = re.compile(
    r"^[a-zA-Z_:][a-zA-Z0-9_:]*"
    r'(\{[a-zA-Z_][a-zA-Z0-9_]*="(\\.|[^"\\\n])*"(,[a-zA-Z_][a-zA-Z0-9_]*="(\\.|[^"\\\n])*")*\})?'
    r" (-?\d+(\.\d+)?([eE][+-]?\d+)?|[+-]?Inf|NaN)$"
)
_TYPE_LINE = re.compile(r"^# TYPE [a-zA-Z_:][a-zA-Z0-9_:]* (counter|gauge|summary|histogram|untyped)$")


def _assert_parses(text):
    seen_sample = False
    for line in text.rstrip("\n").splitlines():
        if not line:
            continue
        assert _TYPE_LINE.match(line) or _SAMPLE.match(line), f"bad exposition line: {line!r}"
        seen_sample = seen_sample or bool(_SAMPLE.match(line))
    return seen_sample


def test_prometheus_renders_real_metrics_snapshot_under_grammar():
    metrics = ServingMetrics()
    for i in range(10):
        metrics.record("POST /predict", 200, 0.001 * (i + 1))
    metrics.record("GET /health", 500, 0.002)
    metrics.inc("shed_inflight")
    metrics.observe_queue_wait("batcher", 0.003)
    text = render_prometheus(metrics.snapshot())
    assert _assert_parses(text)
    assert 'unionml_tpu_route_requests_total{route="POST /predict"} 10' in text
    assert 'unionml_tpu_overload_total{counter="shed_inflight"} 1' in text
    assert 'quantile="0.99"' in text


def test_prometheus_escapes_label_values():
    metrics = ServingMetrics()
    metrics.record('GET /evil"\\\n', 200, 0.001)
    text = render_prometheus(metrics.snapshot())
    assert _assert_parses(text)
    assert '\\"' in text and "\\\\" in text and "\\n" in text
    # no raw newline survives inside any label value
    for line in text.splitlines():
        assert _TYPE_LINE.match(line) or _SAMPLE.match(line)


def test_prometheus_skips_none_and_string_leaves():
    snapshot = {
        "requests_total": 3,
        "errors_total": 0,
        "gauges": {"replicas": None, "name": "llama", "active": True},
        "generation": {"ttft_ms": {"window": 0}},
    }
    text = render_prometheus(snapshot)
    assert _assert_parses(text)
    assert "None" not in text and "llama" not in text
    assert "unionml_tpu_gauges_active 1" in text
    assert "unionml_tpu_generation_ttft_count 0" in text


def test_prometheus_nested_sections_flatten_with_index_labels():
    snapshot = {
        "requests_total": 0,
        "errors_total": 0,
        "generation": {"per_replica": [{"resident": 1}, {"resident": 2}]},
    }
    text = render_prometheus(snapshot)
    assert 'unionml_tpu_generation_per_replica_resident{index="0"} 1' in text
    assert 'unionml_tpu_generation_per_replica_resident{index="1"} 2' in text


def test_prometheus_renders_prefix_cache_section_without_none_gauges():
    # the radix prefix cache's stats() section (serving/continuous.py) must
    # reach the exposition as plain numeric series — every value an int by
    # contract, never a None-valued sample; grammar-checked like the rest
    snapshot = {
        "requests_total": 0,
        "errors_total": 0,
        "generation": {
            "prefix_cache": {
                "hits": 4, "misses": 1, "tokens_avoided": 96, "cow_copies": 1,
                "evictions": 0, "evicted_blocks": 0, "cached_blocks": 7,
                "cached_tokens": 56, "pinned_blocks": 2, "nodes": 3,
            }
        },
    }
    text = render_prometheus(snapshot)
    assert _assert_parses(text)
    assert "None" not in text
    assert "unionml_tpu_generation_prefix_cache_hits 4" in text
    assert "unionml_tpu_generation_prefix_cache_tokens_avoided 96" in text
    assert "unionml_tpu_generation_prefix_cache_pinned_blocks 2" in text


def test_prometheus_renders_quantized_pool_gauges_without_none():
    # the int8-aware byte gauges (serving/continuous.py stats): kv_blocks
    # carries block_bytes/used_bytes plus a STRING dtype label (skipped by the
    # exposition, never rendered as a broken sample), and prefix_cache carries
    # cached_bytes — every numeric leaf an int, never None
    snapshot = {
        "requests_total": 0,
        "errors_total": 0,
        "generation": {
            "kv_blocks": {
                "total": 38, "used": 12, "shared_prefix": 0, "block_size": 16,
                "preemptions": 0, "block_bytes": 8704, "used_bytes": 104448,
                "kv_dtype": "int8",
            },
            "prefix_cache": {
                "hits": 4, "misses": 1, "tokens_avoided": 96, "cow_copies": 1,
                "evictions": 0, "evicted_blocks": 0, "cached_blocks": 7,
                "cached_tokens": 56, "cached_bytes": 60928, "pinned_blocks": 2,
                "nodes": 3,
            },
        },
    }
    text = render_prometheus(snapshot)
    assert _assert_parses(text)
    assert "None" not in text
    assert "unionml_tpu_generation_kv_blocks_block_bytes 8704" in text
    assert "unionml_tpu_generation_kv_blocks_used_bytes 104448" in text
    assert "unionml_tpu_generation_prefix_cache_cached_bytes 60928" in text
    # the dtype label is a string leaf: skipped, not emitted as a series
    assert "kv_dtype" not in text


# ------------------------------------------------------------------ serving app surface


@pytest.fixture
def traced_app(sklearn_model):
    sklearn_model.train(hyperparameters={"max_iter": 500})
    from unionml_tpu.serving.app import ServingApp

    app = ServingApp(sklearn_model)
    app.configure_observability(trace=True, flight_recorder_size=16, access_log=False)
    return app


def _app_dispatch(app, method, path, body=b"", headers=None):
    async def run():
        app.startup()
        return await app.server.dispatch_with_headers(method, path, body, headers)

    return asyncio.run(run())


def test_metrics_prometheus_format_negotiation(traced_app):
    status, payload, content_type, _ = _app_dispatch(traced_app, "GET", "/health")
    assert status == 200
    status, text, content_type, _ = _app_dispatch(traced_app, "GET", "/metrics?format=prometheus")
    assert status == 200
    assert content_type.startswith("text/plain")
    assert _assert_parses(text)
    status, payload, content_type, _ = _app_dispatch(traced_app, "GET", "/metrics")
    assert status == 200 and content_type == "application/json"
    status, payload, _, _ = _app_dispatch(traced_app, "GET", "/metrics?format=xml")
    assert status == 400 and "unknown metrics format" in payload["detail"]


def test_debug_requests_lists_and_filters(traced_app):
    _app_dispatch(traced_app, "GET", "/health", headers={"x-request-id": "h-1"})
    _app_dispatch(traced_app, "GET", "/nope", headers={"x-request-id": "n-1"})
    status, payload, _, _ = _app_dispatch(traced_app, "GET", "/debug/requests")
    assert status == 200 and payload["tracing"] is True
    ids = {s["request_id"] for s in payload["completed"]}
    assert {"h-1", "n-1"} <= ids
    status, payload, _, _ = _app_dispatch(traced_app, "GET", "/debug/requests?route=/health&status=200")
    assert {s["request_id"] for s in payload["completed"]} == {"h-1"}
    status, payload, _, _ = _app_dispatch(traced_app, "GET", "/debug/requests?status=potato")
    assert status == 400
    status, payload, _, _ = _app_dispatch(traced_app, "GET", "/debug/requests?limit=zero")
    assert status == 400


def test_debug_request_by_id_timeline_roundtrip(traced_app):
    _app_dispatch(traced_app, "GET", "/health", headers={"x-request-id": "find-me"})
    status, payload, _, _ = _app_dispatch(traced_app, "GET", "/debug/requests/find-me")
    assert status == 200
    assert payload["request_id"] == "find-me" and payload["route"] == "GET /health"
    assert payload["events"][0]["event"] == "http.accept"
    status, payload, _, _ = _app_dispatch(traced_app, "GET", "/debug/requests/who")
    assert status == 404


def test_debug_request_by_id_hints_when_tracing_off(sklearn_model):
    sklearn_model.train(hyperparameters={"max_iter": 500})
    from unionml_tpu.serving.app import ServingApp

    app = ServingApp(sklearn_model)
    app.configure_observability(trace=False)
    _app_dispatch(app, "GET", "/health", headers={"x-request-id": "gone"})
    status, payload, _, _ = _app_dispatch(app, "GET", "/debug/requests/gone")
    assert status == 404 and "tracing is off" in payload["detail"]


def test_profile_endpoint_requires_configuration(traced_app):
    traced_app.profile_dir = None
    status, payload, _, _ = _app_dispatch(traced_app, "POST", "/debug/profile")
    assert status == 400 and "--profile-dir" in payload["detail"]


def test_profile_endpoint_rejects_overlapping_captures(traced_app, tmp_path, monkeypatch):
    import jax

    calls = []
    monkeypatch.setattr(jax.profiler, "start_trace", lambda d: calls.append(("start", d)))
    monkeypatch.setattr(jax.profiler, "stop_trace", lambda: calls.append(("stop", None)))
    traced_app.profile_dir = str(tmp_path)

    async def overlap():
        traced_app.startup()
        body = json.dumps({"duration_ms": 150}).encode()
        return await asyncio.gather(
            traced_app.server.dispatch_with_headers("POST", "/debug/profile", body),
            traced_app.server.dispatch_with_headers("POST", "/debug/profile", body),
        )

    results = asyncio.run(overlap())
    statuses = sorted(r[0] for r in results)
    assert statuses == [200, 409]
    assert calls == [("start", str(tmp_path)), ("stop", None)]  # exactly one capture
    ok = next(r for r in results if r[0] == 200)
    assert ok[1]["duration_ms"] == 150.0

    status, payload, _, _ = _app_dispatch(
        traced_app, "POST", "/debug/profile", json.dumps({"duration_ms": -5}).encode()
    )
    assert status == 400
    status, payload, _, _ = _app_dispatch(
        traced_app, "POST", "/debug/profile", json.dumps({"duration_ms": "soon"}).encode()
    )
    assert status == 400


# ------------------------------------------------------------------ structured logging


def test_loglevel_garbage_falls_back_to_info():
    """The crash-at-import regression: UNIONML_TPU_LOGLEVEL=garbage must warn
    and degrade, never raise before app code runs."""
    code = (
        "from unionml_tpu._logging import logger; "
        "import logging; print(logger.level == logging.INFO)"
    )
    env = {**os.environ, "UNIONML_TPU_LOGLEVEL": "garbage"}
    proc = subprocess.run(
        [sys.executable, "-c", code], env=env, capture_output=True, text=True, timeout=60
    )
    assert proc.returncode == 0, proc.stderr
    assert proc.stdout.strip() == "True"
    assert "invalid UNIONML_TPU_LOGLEVEL" in proc.stderr


def test_json_formatter_carries_request_id():
    record = logging.LogRecord("unionml_tpu", logging.INFO, __file__, 1, "served %s", ("x",), None)
    line = json.loads(JsonFormatter().format(record))
    assert line["message"] == "served x" and "request_id" not in line

    tokens = trace_mod.bind("corr-1")
    try:
        line = json.loads(JsonFormatter().format(record))
        assert line["request_id"] == "corr-1"
    finally:
        trace_mod.unbind(tokens)


def test_log_format_env_selects_json(tmp_path):
    code = (
        "from unionml_tpu._logging import logger; logger.warning('hello json')"
    )
    env = {**os.environ, "UNIONML_TPU_LOG_FORMAT": "json"}
    proc = subprocess.run(
        [sys.executable, "-c", code], env=env, capture_output=True, text=True, timeout=60
    )
    line = json.loads(proc.stderr.strip().splitlines()[-1])
    assert line["level"] == "WARNING" and line["message"] == "hello json"


def test_set_log_format_toggles_formatter():
    from unionml_tpu._logging import logger

    set_log_format("json")
    try:
        assert all(isinstance(h.formatter, JsonFormatter) for h in logger.handlers)
    finally:
        set_log_format("text")
    assert not any(isinstance(h.formatter, JsonFormatter) for h in logger.handlers)


# ------------------------------------------------- HTTP -> engine propagation


@pytest.fixture(scope="module")
def tiny_gen():
    import jax
    import jax.numpy as jnp

    from unionml_tpu.models import Llama, LlamaConfig

    config = LlamaConfig.tiny(
        vocab_size=97, dim=64, n_layers=2, n_heads=4, n_kv_heads=2, hidden_dim=128,
        dtype=jnp.float32, param_dtype=jnp.float32,
    )
    module = Llama(config)
    params = module.init(jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))["params"]
    return module, params


def _engine(tiny_gen, **kwargs):
    from unionml_tpu.models import GenerationConfig, Generator
    from unionml_tpu.serving import ContinuousBatcher

    module, params = tiny_gen
    cfg = GenerationConfig(max_new_tokens=8, temperature=0.0, prompt_buckets=(16,))
    return ContinuousBatcher(Generator(module, params, cfg), **kwargs)


def _engine_server(batcher):
    """An HTTP server whose POST /gen submits the JSON prompt to the engine
    and drains the stream off-loop — the serving app's stream-predictor shape,
    minus the model plumbing."""
    srv, recorder = _server(enabled=True)

    async def gen_handler(body):
        prompt = json.loads(body or b"{}").get("prompt", [3, 1, 4])
        loop = asyncio.get_running_loop()
        stream = batcher.submit(prompt)  # handler context: trace is ambient here
        tokens = await loop.run_in_executor(
            None, lambda: [int(t) for c in stream for t in np.asarray(c).ravel()]
        )
        return 200, {"tokens": tokens}, "application/json"

    srv.route("POST", "/gen", gen_handler)
    return srv, recorder


def test_request_id_propagates_http_to_engine_timeline(tiny_gen):
    batcher = _engine(tiny_gen, slots=2, decode_chunk=4)
    try:
        srv, recorder = _engine_server(batcher)
        body = json.dumps({"prompt": [3, 14, 15, 92, 6]}).encode()
        status, payload, _, extra = _dispatch(
            srv, "POST", "/gen", body, {"x-request-id": "gen-1"}
        )
        assert status == 200 and extra["X-Request-Id"] == "gen-1"
        assert payload["tokens"]
        snap = recorder.get("gen-1")
        names = [e["event"] for e in snap["events"]]
        # the full lifecycle, in causal order, on ONE timeline
        for required in (
            "http.accept", "engine.submit", "engine.admission_start",
            "engine.prefill", "engine.first_token", "engine.emit", "engine.finish",
        ):
            assert required in names, f"missing {required} in {names}"
        assert names.index("engine.submit") < names.index("engine.admission_start")
        assert names.index("engine.first_token") <= names.index("engine.emit")
        offsets = [e["t_ms"] for e in snap["events"]]
        assert offsets == sorted(offsets)  # monotonic-clock offsets, one clock
        admission = next(e for e in snap["events"] if e["event"] == "engine.admission_start")
        assert admission["queue_wait_ms"] >= 0
        emitted = sum(e["tokens"] for e in snap["events"] if e["event"] == "engine.emit")
        assert emitted == len(payload["tokens"])
    finally:
        batcher.close()


def test_chunked_prefill_records_every_chunk(tiny_gen):
    batcher = _engine(tiny_gen, slots=1, decode_chunk=4, admit_chunk=8)
    try:
        srv, recorder = _engine_server(batcher)
        body = json.dumps({"prompt": list(range(1, 15))}).encode()  # aligned to 16 -> 2 chunks
        status, _, _, _ = _dispatch(srv, "POST", "/gen", body, {"x-request-id": "chunked"})
        assert status == 200
        chunks = [
            e for e in recorder.get("chunked")["events"] if e["event"] == "engine.prefill_chunk"
        ]
        assert [c["pos"] for c in chunks] == [8, 16]
        assert all(c["chunk"] == 8 and c["width"] == 16 for c in chunks)
    finally:
        batcher.close()


def test_engine_shed_paths_trace_and_echo_request_id(tiny_gen):
    batcher = _engine(tiny_gen, slots=1, max_waiting=1)
    try:
        srv, recorder = _engine_server(batcher)
        # occupy the only slot, then fill the 1-deep waiting queue: the HTTP
        # submit must shed 429 with the id echoed and both layers traced
        occupant = batcher.submit([5, 5, 5])
        next(iter(occupant))
        waiter = batcher.submit([6, 6])
        status, _, _, extra = _dispatch(srv, "POST", "/gen", b"{}", {"x-request-id": "shed-q"})
        assert (status, extra["X-Request-Id"]) == (429, "shed-q")
        events = recorder.get("shed-q")["events"]
        assert any(e["event"] == "engine.shed_queue_full" for e in events)
        assert any(
            e["event"] == "http.shed" and e["reason"] == "queue_full" for e in events
        )
        for stream in (occupant, waiter):
            for _ in stream:
                pass
    finally:
        batcher.close()


def test_engine_deadline_shed_traces_503(tiny_gen):
    import time as _time

    batcher = _engine(tiny_gen, slots=1)
    try:
        srv, recorder = _engine_server(batcher)

        async def expired_handler(body):
            batcher.submit([1, 2, 3], deadline=_time.monotonic() - 1.0)
            raise AssertionError("unreachable")

        srv.route("POST", "/expired", expired_handler)
        status, _, _, extra = _dispatch(srv, "POST", "/expired", b"", {"x-request-id": "late"})
        assert (status, extra["X-Request-Id"]) == (503, "late")
        events = recorder.get("late")["events"]
        shed = next(e for e in events if e["event"] == "engine.shed_deadline")
        assert shed["phase"] == "submit"
        assert any(e["event"] == "http.shed" and e["reason"] == "deadline" for e in events)
    finally:
        batcher.close()


def test_engine_trace_opt_out_even_with_ambient_trace(tiny_gen):
    """trace=False on the engine (the bench lane's control arm) must not
    touch an ambient request trace."""
    batcher = _engine(tiny_gen, slots=1, trace=False)
    try:
        trace = RequestTrace("ambient", "POST", "/gen")
        tokens = trace_mod.bind("ambient", trace)
        try:
            stream = batcher.submit([4, 2])
        finally:
            trace_mod.unbind(tokens)
        drained = [int(t) for c in stream for t in np.asarray(c).ravel()]
        assert drained
        assert [e["event"] for e in trace.snapshot()["events"]] == []
    finally:
        batcher.close()
