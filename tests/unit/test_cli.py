"""CLI tests — modeled on the reference's CLI surface (unionml/cli.py:26-212):
init renders a project, deploy/train/predict/list-model-versions/fetch-model run the
remote path end-to-end against a temp backend store, and serve guards its env var."""

import json
from pathlib import Path

import pytest
from click.testing import CliRunner

from unionml_tpu.cli import app
from unionml_tpu.templating import list_templates, render_template, validate_app_name


def test_templating_list_and_validate():
    names = list_templates()
    assert {"basic", "basic-serverless", "image-classification"} <= set(names)
    validate_app_name("my-app_1")
    with pytest.raises(ValueError):
        validate_app_name("1bad")
    with pytest.raises(ValueError):
        validate_app_name("bad name")


def test_init_renders_template(tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)
    runner = CliRunner()
    result = runner.invoke(app, ["init", "my_digits_app", "--template", "basic"])
    assert result.exit_code == 0, result.output
    project = tmp_path / "my_digits_app"
    assert (project / "app.py").exists()
    assert "my_digits_app" in (project / "README.md").read_text()
    assert "{{app_name}}" not in (project / "app.py").read_text()


def test_init_rejects_existing_dir(tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)
    (tmp_path / "dup_app").mkdir()
    result = CliRunner().invoke(app, ["init", "dup_app"])
    assert result.exit_code != 0


def test_render_template_unknown():
    with pytest.raises(ValueError, match="unknown template"):
        render_template("nope", "x_app", Path("/tmp"))


def test_deploy_train_predict_roundtrip(cli_project):
    runner = CliRunner()
    result = runner.invoke(app, ["deploy", "cli_app:model", "--allow-uncommitted"])
    assert result.exit_code == 0, result.output
    assert "Deployed" in result.output

    result = runner.invoke(app, ["train", "cli_app:model", "-i", json.dumps({"hyperparameters": {"max_iter": 500}})])
    assert result.exit_code == 0, result.output
    assert "Metrics" in result.output

    result = runner.invoke(app, ["list-model-versions", "cli_app:model"])
    assert result.exit_code == 0, result.output
    assert "- train-" in result.output

    features = [{"x0": 1.0, "x1": 2.0}, {"x0": 3.0, "x1": 1.0}]
    features_file = cli_project / "features.json"
    features_file.write_text(json.dumps(features))
    result = runner.invoke(app, ["predict", "cli_app:model", "--features", str(features_file)])
    assert result.exit_code == 0, result.output
    assert "Predictions" in result.output

    out_file = cli_project / "fetched.joblib"
    result = runner.invoke(app, ["fetch-model", "cli_app:model", "-o", str(out_file)])
    assert result.exit_code == 0, result.output
    assert out_file.exists()


def test_serve_rejects_preset_env(cli_project, monkeypatch, tmp_path):
    model_file = tmp_path / "m.joblib"
    model_file.write_text("x")
    monkeypatch.setenv("UNIONML_MODEL_PATH", "/somewhere")
    result = CliRunner().invoke(app, ["serve", "cli_app:model", "--model-path", str(model_file)])
    assert result.exit_code != 0
    assert "already set" in result.output


def test_serve_requires_existing_model_path(cli_project):
    result = CliRunner().invoke(app, ["serve", "cli_app:model", "--model-path", "/does/not/exist"])
    assert result.exit_code != 0
    assert "does not exist" in result.output


def test_serve_cluster_flags_validate_and_export_early(cli_project, monkeypatch):
    """The --num-hosts/--coordinator/--process-id trio: usage errors fail NOW
    (before any app import), and valid flags export the distributed env vars
    under the --dp-replicas early-export contract."""
    import os

    runner = CliRunner()
    result = runner.invoke(app, ["serve", "cli_app:model", "--num-hosts", "0"])
    assert result.exit_code != 0 and "--num-hosts" in result.output
    result = runner.invoke(app, ["serve", "cli_app:model", "--num-hosts", "2"])
    assert result.exit_code != 0 and "--coordinator" in result.output
    result = runner.invoke(
        app,
        ["serve", "cli_app:model", "--num-hosts", "2", "--coordinator", "h:1", "--process-id", "2"],
    )
    assert result.exit_code != 0 and "--process-id" in result.output
    # a VALID trio exports before the app module imports; the app itself then
    # fails later (no artifact), which is how we observe the export without
    # actually forming a 2-process runtime in a unit test
    for name in ("UNIONML_TPU_COORDINATOR", "UNIONML_TPU_NUM_PROCESSES", "UNIONML_TPU_PROCESS_ID"):
        monkeypatch.delenv(name, raising=False)
    result = runner.invoke(
        app,
        ["serve", "cli_app:model", "--num-hosts", "2", "--coordinator", "127.0.0.1:9",
         "--process-id", "1", "--workers", "2"],
    )
    assert result.exit_code != 0
    assert "--workers does not compose" in result.output
    assert os.environ.get("UNIONML_TPU_COORDINATOR") == "127.0.0.1:9"
    assert os.environ.get("UNIONML_TPU_NUM_PROCESSES") == "2"
    assert os.environ.get("UNIONML_TPU_PROCESS_ID") == "1"
    for name in ("UNIONML_TPU_COORDINATOR", "UNIONML_TPU_NUM_PROCESSES", "UNIONML_TPU_PROCESS_ID"):
        # plain pop, NOT monkeypatch.delenv: the CLI set these AFTER the
        # earlier delenv, so monkeypatch would faithfully RESTORE them at
        # teardown and leak a fake 2-process fleet env into later tests
        os.environ.pop(name, None)


def test_serve_fault_tolerance_flags_validate_and_export_early(cli_project, monkeypatch):
    """The --fault-plan/--probe-interval/--probation-probes/--lease-ttl
    quartet: usage errors fail NOW, and valid flags export the fault-
    tolerance env vars under the --dp-replicas early-export contract."""
    import os

    runner = CliRunner()
    result = runner.invoke(app, ["serve", "cli_app:model", "--fault-plan", "not json {"])
    assert result.exit_code != 0 and "--fault-plan" in result.output
    result = runner.invoke(app, ["serve", "cli_app:model", "--probe-interval", "0"])
    assert result.exit_code != 0 and "--probe-interval" in result.output
    result = runner.invoke(app, ["serve", "cli_app:model", "--probation-probes", "0"])
    assert result.exit_code != 0 and "--probation-probes" in result.output
    result = runner.invoke(app, ["serve", "cli_app:model", "--lease-ttl", "-1"])
    assert result.exit_code != 0 and "--lease-ttl" in result.output
    names = (
        "UNIONML_TPU_FAULT_PLAN", "UNIONML_TPU_PROBE_INTERVAL_S",
        "UNIONML_TPU_PROBATION_PROBES", "UNIONML_TPU_LEASE_TTL_S",
    )
    for name in names:
        monkeypatch.delenv(name, raising=False)
    plan = '{"events": [{"t": 0.5, "kind": "worker_kill", "host": 1}]}'
    result = runner.invoke(
        app,
        ["serve", "cli_app:model", "--fault-plan", plan, "--probe-interval", "0.5",
         "--probation-probes", "3", "--lease-ttl", "2.0",
         "--model-path", "/does/not/exist"],  # fails AFTER the export
    )
    assert result.exit_code != 0
    assert os.environ.get("UNIONML_TPU_FAULT_PLAN") == plan
    assert os.environ.get("UNIONML_TPU_PROBE_INTERVAL_S") == "0.5"
    assert os.environ.get("UNIONML_TPU_PROBATION_PROBES") == "3"
    assert os.environ.get("UNIONML_TPU_LEASE_TTL_S") == "2.0"
    for name in names:
        # plain pop (see the cluster-flags test): the CLI set these after
        # delenv, so monkeypatch would restore them and leak chaos into
        # later tests
        os.environ.pop(name, None)


def test_replay_fault_plan_requires_self_host():
    result = CliRunner().invoke(
        app,
        ["replay", "scenario:chaos_fleet", "--target", "http://127.0.0.1:9",
         "--fault-plan", '{"events": []}'],
    )
    assert result.exit_code != 0
    assert "--self-host" in result.output


def test_app_source_files_snapshot(cli_project):
    from unionml_tpu.cli import _app_source_files

    files = _app_source_files("cli_app:model")
    assert any(p.name == "cli_app.py" for p in files)
    (cli_project / "cli_app.py").write_text((cli_project / "cli_app.py").read_text() + "\n# touched\n")
    assert _app_source_files("cli_app:model") != files
