"""Fleet fault tolerance (docs/serving.md "Fault tolerance"): deterministic
fault injection, the host lifecycle state machine, stream-failure semantics,
and coordinator failover over the rendezvous dir.

Everything here runs in ONE process with real control-plane HTTP (a
WorkerAgent behind a RemoteHost handle) — the fault plan (serving/faults.py)
is what stands in for SIGKILL, so every transition is driven, not raced:

- **plan**: schema round trip, version fencing, seeded determinism;
- **lifecycle**: ``live → suspect`` on an injected drop, ``→ dead`` after
  the probe-failure streak, ``→ probation → live`` once the fault window
  closes (virtual time on an injectable clock);
- **streams**: a host that dies before the first token costs ONE transparent
  retry on a sibling (token-identical to the oracle); a host that dies after
  tokens flowed raises the clean 503-shaped :class:`StreamInterrupted`;
- **failover**: the fenced checkpoint/lease files, lease-expiry promotion of
  the lowest-id live worker, and the zombie coordinator's writes rejected;
- **hygiene**: graceful shutdown withdraws the rendezvous announce, and
  stale-epoch announces from a previous fleet generation are ignored.

The cross-PROCESS leg (SIGKILL a real worker subprocess, restart it, rejoin
through probation) lives in tests/emulated/test_cluster.py.
"""

import json
import time

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from unionml_tpu.models import GenerationConfig, Generator, Llama, LlamaConfig
from unionml_tpu.serving import ContinuousBatcher
from unionml_tpu.serving.cluster import (
    FleetCoordinator,
    HOST_DEAD,
    HOST_LIVE,
    HOST_PROBATION,
    HOST_SUSPECT,
    LocalHost,
    RemoteHost,
    StreamInterrupted,
    WorkerAgent,
    connect_fleet,
    lease_expired,
    maybe_promote,
    read_checkpoint,
    read_lease,
    write_checkpoint,
    write_lease,
)
from unionml_tpu.serving.faults import (
    ArmedFaultPlan,
    FaultEvent,
    FaultInjected,
    FaultPlan,
    default_chaos_plan,
)


@pytest.fixture(scope="module")
def tiny():
    config = LlamaConfig.tiny(
        vocab_size=96, dim=64, n_layers=2, n_heads=4, n_kv_heads=2, hidden_dim=128,
        dtype=jnp.float32, param_dtype=jnp.float32,
    )
    module = Llama(config)
    params = module.init(jax.random.PRNGKey(1), jnp.zeros((1, 8), jnp.int32))["params"]
    return module, params


def _cfg(**overrides):
    kwargs = dict(max_new_tokens=8, temperature=0.0, prompt_buckets=(16,))
    kwargs.update(overrides)
    return GenerationConfig(**kwargs)


def _engine(tiny, cfg, **kwargs):
    module, params = tiny
    knobs = dict(slots=2, decode_chunk=4, block_size=8, pool_blocks=64)
    knobs.update(kwargs)
    return ContinuousBatcher(Generator(module, params, cfg), **knobs)


def _drain(stream):
    return [int(t) for chunk in stream for t in np.asarray(chunk).ravel()]


def _expected(tiny, cfg, prompts):
    module, params = tiny
    gen = Generator(module, params, cfg)
    return [list(map(int, gen([p])[0])) for p in prompts]


PROMPTS = [[3, 1, 4, 1, 5], [9, 2, 6, 5, 3, 5, 8, 9], [7, 1]]


class _Clock:
    """Injectable virtual clock for armed plans (real monotonic elsewhere)."""

    def __init__(self, now=0.0):
        self.now = float(now)

    def __call__(self):
        return self.now


# ------------------------------------------------------------------- fault plans


def test_fault_plan_schema_round_trip_and_validation():
    plan = FaultPlan.parse(json.dumps({
        "version": 1, "seed": 7, "events": [
            {"t": 1.0, "kind": "worker_kill", "host": 1, "for_s": 2.0},
            {"t": 0.5, "kind": "rpc_drop", "host": 0},
            {"t": 2.0, "kind": "rpc_delay", "delay_s": 0.01},
            {"t": 3.0, "kind": "stream_cut", "host": 1, "after_tokens": 2},
        ],
    }))
    assert plan.seed == 7
    assert [e.kind for e in plan.events] == [
        "rpc_drop", "worker_kill", "rpc_delay", "stream_cut"
    ]  # sorted by onset
    assert plan.horizon_s == pytest.approx(3.25)
    assert plan.fault_times() == [0.5, 1.0, 3.0]  # rpc_delay is not disruptive
    # canonical text survives a round trip
    assert FaultPlan.parse(plan.dumps()).dumps() == plan.dumps()
    with pytest.raises(ValueError, match="version"):
        FaultPlan.parse('{"version": 99, "events": []}')
    with pytest.raises(ValueError, match="kind"):
        FaultPlan.parse('{"events": [{"t": 0, "kind": "meteor"}]}')
    with pytest.raises(ValueError, match="events"):
        FaultPlan.parse('{"seed": 1}')
    with pytest.raises(ValueError, match="JSON"):
        FaultPlan.parse("not json")


def test_fault_plan_env_reader_degrades_on_garbage(monkeypatch):
    from unionml_tpu.defaults import SERVE_FAULT_PLAN_ENV_VAR

    monkeypatch.delenv(SERVE_FAULT_PLAN_ENV_VAR, raising=False)
    assert FaultPlan.from_env() is None
    monkeypatch.setenv(SERVE_FAULT_PLAN_ENV_VAR, '{"events": [{"t": 0, "kind": "rpc_drop"}]}')
    plan = FaultPlan.from_env()
    assert plan is not None and plan.events[0].kind == "rpc_drop"
    monkeypatch.setenv(SERVE_FAULT_PLAN_ENV_VAR, "/nonexistent/plan.json")
    assert FaultPlan.from_env() is None  # warn-and-degrade, never a crash
    monkeypatch.setenv(SERVE_FAULT_PLAN_ENV_VAR, '{"events": "nope"}')
    assert FaultPlan.from_env() is None


def test_armed_plan_is_deterministic_and_windowed():
    clock = _Clock()
    plan = FaultPlan([
        FaultEvent(1.0, "rpc_drop", host=1, for_s=2.0),
        FaultEvent(5.0, "rpc_delay", host=None, for_s=1.0, delay_s=0.0),
    ], seed=3)
    armed = plan.arm(clock=clock)
    armed.check_rpc(1)  # before the window: no-op
    clock.now = 1.5
    with pytest.raises(FaultInjected):
        armed.check_rpc(1)
    armed.check_rpc(0)  # scoped to host 1
    clock.now = 3.5
    armed.check_rpc(1)  # window closed
    clock.now = 5.5
    armed.check_rpc(1)  # rpc_delay with delay_s=0: counted, not raised
    stats = armed.stats()
    assert stats == {
        "worker_kill": 0, "rpc_drop": 1, "rpc_delay": 1, "stream_cut": 0, "events": 2,
    }
    # seeded probabilistic drops: identical draw sequences for identical seeds
    probabilistic = FaultPlan([FaultEvent(0.0, "rpc_drop", for_s=100.0, p=0.5)], seed=11)

    def outcomes(armed_plan):
        out = []
        for _ in range(32):
            try:
                armed_plan.check_rpc(0)
                out.append(False)
            except FaultInjected:
                out.append(True)
        return out

    first = outcomes(probabilistic.arm(clock=_Clock(1.0)))
    second = outcomes(probabilistic.arm(clock=_Clock(1.0)))
    assert first == second and True in first and False in first


def test_default_chaos_plan_shape():
    plan = default_chaos_plan(seed=5)
    kinds = [e.kind for e in plan.events]
    assert kinds == ["rpc_drop", "worker_kill"]
    assert all(e.host == 1 for e in plan.events)
    assert plan.fault_times() == [e.t for e in plan.events]


# --------------------------------------------------------------- host lifecycle


def test_suspect_dead_probation_live_under_injected_drop(tiny):
    """The whole lifecycle, driven: an rpc_drop window suspects the host and
    the probe-failure streak kills it; when the window closes, probation
    probes + warmup bring it back — and the fleet counters/rows tell the
    story on stats()."""
    cfg = _cfg()
    e0, e1 = _engine(tiny, cfg), _engine(tiny, cfg)
    agent = WorkerAgent(e1, process_id=1).start()
    coordinator = FleetCoordinator(
        [LocalHost(e0, host_id=0), RemoteHost(agent.address, host_id=1)],
        probation_probes=2, dead_after=2, probe_interval_s=0.05,
    )
    clock = _Clock(0.0)
    armed = ArmedFaultPlan(
        FaultPlan([FaultEvent(1.0, "rpc_drop", host=1, for_s=10.0)]), clock=clock
    )
    coordinator._faults = armed
    coordinator.hosts[1].faults = armed
    host = coordinator.hosts[1]
    try:
        # live: traffic reaches both hosts
        assert _drain(coordinator.submit(PROMPTS[0])) == _expected(tiny, cfg, PROMPTS[:1])[0]
        assert host.state == HOST_LIVE

        clock.now = 2.0  # the drop window opens
        got = [_drain(coordinator.submit(p)) for p in PROMPTS]
        assert got == _expected(tiny, cfg, PROMPTS)  # routed around, zero sheds
        assert host.state == HOST_SUSPECT
        assert host.suspects == 1
        assert host.rpc_retries >= 1  # the idempotent probe retried first

        # reconciliation probes fail inside the window: suspect -> dead
        coordinator.reconcile_once()
        coordinator.reconcile_once()
        assert host.state == HOST_DEAD

        clock.now = 20.0  # the window closes; the worker is reachable again
        coordinator.reconcile_once()
        assert host.state == HOST_PROBATION  # first success: probation, not live
        assert host.alive is False  # probation takes no traffic yet
        coordinator.reconcile_once()  # second success reaches the streak + warmup
        assert host.state == HOST_LIVE
        assert host.rejoins == 1

        stats = coordinator.stats()
        fleet = stats["fleet"]
        assert fleet["host_suspects"] == 1
        assert fleet["host_rejoins"] == 1
        assert fleet["rpc_retries"] >= 1
        assert fleet["recovery_ms"]["window"] == 1
        assert fleet["states"][HOST_LIVE] == 2
        assert fleet["faults_injected"]["rpc_drop"] >= 1
        census = coordinator.host_census()
        assert census[1]["state"] == HOST_LIVE
        assert census[1]["last_transition_s"] >= 0.0
        # the rejoined host takes traffic again
        assert _drain(coordinator.submit(PROMPTS[2])) == _expected(tiny, cfg, PROMPTS[2:])[0]
    finally:
        coordinator.stop_reconciler()
        agent.close(close_engine=True)
        e0.close(wait=False)


def test_zero_token_stream_retries_on_sibling(tiny):
    """A host that dies BEFORE the first token costs one transparent retry:
    the consumer sees the full, oracle-identical stream from the sibling."""
    cfg = _cfg()
    e0, e1 = _engine(tiny, cfg), _engine(tiny, cfg)
    a0 = WorkerAgent(e0, process_id=0).start()
    a1 = WorkerAgent(e1, process_id=1).start()
    coordinator = FleetCoordinator([
        RemoteHost(a0.address, host_id=0), RemoteHost(a1.address, host_id=1),
    ])
    clock = _Clock(5.0)
    armed = ArmedFaultPlan(
        # cut host 0's NEXT stream before its first token, inside the window
        FaultPlan([FaultEvent(0.0, "stream_cut", host=0, for_s=100.0, after_tokens=0)]),
        clock=clock,
    )
    coordinator.hosts[0].faults = armed
    coordinator._faults = armed
    try:
        got = _drain(coordinator.submit(PROMPTS[0]))  # ties route to host 0 first
        assert got == _expected(tiny, cfg, PROMPTS[:1])[0]
        assert coordinator.stream_retries == 1
        assert coordinator.streams_interrupted == 0
        assert coordinator.hosts[0].state == HOST_SUSPECT
        assert coordinator.stats()["fleet"]["recovery_ms"]["window"] == 1
    finally:
        a0.close(close_engine=True)
        a1.close(close_engine=True)


def test_emitted_stream_interrupts_cleanly_not_silently(tiny):
    """A host that dies AFTER tokens flowed must not hang and must not be
    silently restitched (the sibling's sampling state differs): the stream
    raises the 503-shaped StreamInterrupted carrying the emitted count."""
    cfg = _cfg(max_new_tokens=16)
    e0 = _engine(tiny, cfg)
    a0 = WorkerAgent(e0, process_id=0).start()
    coordinator = FleetCoordinator([RemoteHost(a0.address, host_id=0)])
    clock = _Clock(5.0)
    armed = ArmedFaultPlan(
        FaultPlan([FaultEvent(0.0, "stream_cut", host=0, for_s=100.0, after_tokens=1)]),
        clock=clock,
    )
    coordinator.hosts[0].faults = armed
    try:
        stream = coordinator.submit(PROMPTS[0])
        received = []
        with pytest.raises(StreamInterrupted) as excinfo:
            for chunk in stream:
                received.extend(int(t) for t in np.asarray(chunk).ravel())
        assert received  # tokens DID flow before the cut
        assert excinfo.value.emitted == len(received)
        assert excinfo.value.status == 503
        assert coordinator.streams_interrupted == 1
        assert coordinator.stream_retries == 0
    finally:
        a0.close(close_engine=True)


# ------------------------------------------------------- checkpoint, lease, fencing


def test_checkpoint_and_lease_fencing_rejects_zombie_epoch(tmp_path):
    root = tmp_path / "fleet"
    assert write_checkpoint(root, epoch=2, num_hosts=2, roster=[]) is True
    assert read_checkpoint(root)["epoch"] == 2
    # a zombie (lower epoch) cannot clobber the successor's checkpoint
    assert write_checkpoint(root, epoch=1, num_hosts=2, roster=[]) is False
    assert read_checkpoint(root)["epoch"] == 2
    # same epoch re-writes (the owner's own heartbeat) are allowed
    assert write_checkpoint(root, epoch=2, num_hosts=2, roster=[], failovers=1) is True
    assert write_lease(root, epoch=2, owner=0, ttl_s=30.0) is True
    assert write_lease(root, epoch=1, owner=1, ttl_s=30.0) is False
    lease = read_lease(root)
    assert lease["epoch"] == 2 and lease["owner"] == 0
    assert lease_expired(lease) is False
    assert lease_expired(None) is True
    assert lease_expired({"expires_at": 1.0}) is True


def test_lease_expiry_promotes_lowest_id_worker_with_fencing(tiny, tmp_path):
    """Coordinator failover end to end: coordinator A (epoch N) stops
    heartbeating; once the lease expires, worker 1 — the lowest-id live
    worker — promotes via connect_fleet with the epoch bumped, and A's
    subsequent rendezvous writes are rejected (fencing). A stream accepted
    on the surviving host before the failover drains untouched."""
    cfg = _cfg()
    root = tmp_path / "fleet"
    e0, e1 = _engine(tiny, cfg), _engine(tiny, cfg)
    agent1 = WorkerAgent(e1, process_id=1).start()
    agent1.announce(root)
    coordinator_a = connect_fleet(
        root, num_hosts=2, timeout_s=10.0, local_engine=e0, local_process_id=0,
        lease_ttl_s=0.2, start_reconciler=False,
    )
    try:
        assert coordinator_a.epoch == 1
        assert read_lease(root)["epoch"] == 1
        # a stream accepted before the failover, drained after it: untouched
        inflight = coordinator_a.hosts[1].submit(PROMPTS[1])

        # while the lease is FRESH, promotion stands down
        assert maybe_promote(
            root, local_engine=e1, local_process_id=1, timeout_s=1.0
        ) is None

        time.sleep(0.3)  # coordinator A "dies": no heartbeat; the lease expires
        assert lease_expired(read_lease(root)) is True
        coordinator_b = maybe_promote(
            root, local_engine=e1, local_process_id=1, timeout_s=1.0,
            start_reconciler=False,
        )
        assert coordinator_b is not None
        try:
            assert coordinator_b.epoch == 2
            assert coordinator_b.coordinator_failovers == 1
            assert read_checkpoint(root)["epoch"] == 2
            assert read_checkpoint(root)["failovers"] == 1
            assert coordinator_b.stats()["fleet"]["coordinator_failovers"] == 1
            # the zombie's writes are rejected, and its own heartbeat path
            # observes the fence
            assert write_lease(root, epoch=coordinator_a.epoch, owner=0, ttl_s=0.2) is False
            coordinator_a._heartbeat_lease()
            assert coordinator_a.fenced is True
            assert read_lease(root)["epoch"] == 2
            # host 0 (coordinator A's local engine) never announced: the
            # promoted roster carries it dead, host 1 serves
            assert coordinator_b.hosts[0].state == HOST_DEAD
            assert _drain(coordinator_b.submit(PROMPTS[0])) == _expected(tiny, cfg, PROMPTS[:1])[0]
            # the pre-failover stream finishes exactly
            assert _drain(inflight) == _expected(tiny, cfg, PROMPTS[1:2])[0]
        finally:
            coordinator_b.stop_reconciler()
    finally:
        coordinator_a.stop_reconciler()
        agent1.close(close_engine=True)
        e0.close(wait=False)


def test_promotion_defers_to_lower_id_live_worker(tiny, tmp_path):
    cfg = _cfg()
    root = tmp_path / "fleet"
    e0, e1 = _engine(tiny, cfg), _engine(tiny, cfg)
    agent0 = WorkerAgent(e0, process_id=0).start()
    agent0.announce(root)
    write_checkpoint(root, epoch=1, num_hosts=2, roster=[])
    write_lease(root, epoch=1, owner=0, ttl_s=0.05)
    time.sleep(0.1)  # expired — but worker 0 is alive and lower-id
    try:
        assert maybe_promote(
            root, local_engine=e1, local_process_id=1, timeout_s=1.0
        ) is None
    finally:
        agent0.close(close_engine=True)
        e1.close(wait=False)


# ------------------------------------------------------------ rendezvous hygiene


def test_graceful_shutdown_withdraws_announce(tiny, tmp_path):
    cfg = _cfg()
    root = tmp_path / "fleet"
    engine = _engine(tiny, cfg)
    agent = WorkerAgent(engine, process_id=0).start()
    path = agent.announce(root)
    assert path.exists()
    agent.close(close_engine=True)
    assert not path.exists()  # a restarted fleet can never ping this address


def test_connect_fleet_rejects_stale_epoch_announces(tiny, tmp_path):
    """Announces stamped below the persisted checkpoint epoch are a previous
    fleet generation's leftovers: connect_fleet must time out rather than
    ping a dead address — and a FRESH announce (stamped from the current
    checkpoint) connects normally."""
    cfg = _cfg()
    root = tmp_path / "fleet"
    root.mkdir()
    write_checkpoint(root, epoch=3, num_hosts=1, roster=[])
    # a stale generation-1 leftover pointing at a long-dead port
    (root / "host-0.json").write_text(json.dumps({
        "process_id": 0, "host": "127.0.0.1", "port": 9, "pid": 1, "epoch": 1,
    }))
    with pytest.raises(TimeoutError):
        connect_fleet(root, num_hosts=1, timeout_s=0.4, start_reconciler=False)
    engine = _engine(tiny, cfg)
    agent = WorkerAgent(engine, process_id=0).start()
    agent.announce(root)  # stamps the checkpoint's epoch (3)
    coordinator = connect_fleet(root, num_hosts=1, timeout_s=10.0, start_reconciler=False)
    try:
        assert coordinator.hosts[0].epoch == 3
        assert coordinator.epoch == 4  # floor + 1
    finally:
        agent.close(close_engine=True)


def test_reconciler_rebinds_replacement_worker_through_probation(tiny, tmp_path):
    """The in-process replacement story the emulated suite pins across real
    processes: the worker dies (dead), a NEW incarnation announces at a new
    address with a fresh epoch, and reconciliation rebinds the handle through
    probation back to live — token-identical service resumes."""
    cfg = _cfg()
    root = tmp_path / "fleet"
    e0, e1 = _engine(tiny, cfg), _engine(tiny, cfg)
    agent = WorkerAgent(e1, process_id=1).start()
    agent.announce(root)
    coordinator = connect_fleet(
        root, num_hosts=2, timeout_s=10.0, local_engine=e0, local_process_id=0,
        start_reconciler=False, probation_probes=2, dead_after=2,
    )
    host = coordinator.hosts[1]
    try:
        agent.close(close_engine=False)  # the worker process "dies" (announce withdrawn)
        with pytest.raises(Exception):
            host.ping(timeout=1.0)
        assert host.state == HOST_SUSPECT
        coordinator.reconcile_once()
        coordinator.reconcile_once()
        assert host.state == HOST_DEAD

        replacement = WorkerAgent(e1, process_id=1).start()  # new port, same id
        replacement.announce(root)
        try:
            coordinator.reconcile_once()  # scan rebinds + first probation probe
            assert host.state == HOST_PROBATION
            assert host.address == replacement.address
            coordinator.reconcile_once()
            assert host.state == HOST_LIVE
            assert host.rejoins == 1
            got = [_drain(coordinator.submit(p)) for p in PROMPTS]
            assert got == _expected(tiny, cfg, PROMPTS)
            # the rebound host is probed and routable again (sequential
            # submits tie-break to host 0; the probe proves readmission)
            probes = coordinator._probe_all(coordinator._live(), PROMPTS[0])
            assert 1 in probes
        finally:
            replacement.close(close_engine=True)
    finally:
        coordinator.stop_reconciler()
        e0.close(wait=False)


# ------------------------------------------------------------------ surfaces


def test_fleet_stats_section_is_none_free_and_prometheus_renders(tiny):
    from unionml_tpu.observability.prometheus import render

    cfg = _cfg()
    engine = _engine(tiny, cfg)
    coordinator = FleetCoordinator([LocalHost(engine, host_id=0)])
    try:
        stats = coordinator.stats()
        fleet = stats["fleet"]
        assert fleet["epoch"] == 0 and fleet["fenced"] == 0
        assert fleet["recovery_ms"] == {"window": 0}
        assert "faults_injected" not in fleet  # absent without a plan, never None

        def no_none(obj):
            if isinstance(obj, dict):
                return all(no_none(v) for v in obj.values())
            if isinstance(obj, list):
                return all(no_none(v) for v in obj)
            return obj is not None

        # the NEW surfaces are strictly None-free (pre-existing engine gauges
        # like rows_per_dispatch may be None pre-traffic; the exposition
        # renderer skips those by contract)
        assert no_none(fleet)
        assert no_none(coordinator.host_census())
        assert no_none(coordinator.replica_loads())
        text = render({"generation": stats})
        assert "fleet" in text and " None" not in text
        health = coordinator.health()
        assert health["replicas"][0]["host_state"] == HOST_LIVE
        assert health["replicas"][0]["last_transition_s"] == 0.0
    finally:
        engine.close(wait=False)
