"""Unit-ring conftest.

The shared app fixtures (synthetic frame + sklearn LogisticRegression app,
``cli_project``) live in tests/conftest.py so the integration ring reuses them —
the analog of the reference's fixture re-export conftest
(/root/reference/tests/unit/conftest.py:1-7).
"""

import pytest


@pytest.fixture(scope="session")
def micro_lm():
    """Vocab-6 Llama for exhaustive-search oracles — small enough that every
    token sequence can be enumerated (shared by test_beam and the constrained
    beam oracles in test_structured; one definition so the micro-model shape
    cannot drift between the two files)."""
    import jax
    import jax.numpy as jnp

    from unionml_tpu.models import Llama, LlamaConfig

    config = LlamaConfig.tiny(
        vocab_size=6, dim=32, n_layers=2, n_heads=4, n_kv_heads=2, hidden_dim=64,
        dtype=jnp.float32, param_dtype=jnp.float32,
    )
    module = Llama(config)
    params = module.init(jax.random.PRNGKey(0), jnp.zeros((1, 4), jnp.int32))["params"]
    return module, params, config
