"""Unit-ring conftest.

The shared app fixtures (synthetic frame + sklearn LogisticRegression app,
``cli_project``) live in tests/conftest.py so the integration ring reuses them —
the analog of the reference's fixture re-export conftest
(/root/reference/tests/unit/conftest.py:1-7).
"""
