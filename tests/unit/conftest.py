"""Shared fixtures: in-memory readers + an sklearn digits-style app.

Mirrors the reference fixture architecture (tests/unit/{dataset_fixtures,
model_fixtures}.py): a 100-row synthetic DataFrame, a LogisticRegression
trainer/predictor/evaluator, and no mocking of the execution substrate — local graphs
run the real engine in-process.
"""

from typing import List

import numpy as np
import pandas as pd
import pytest

from unionml_tpu import Dataset, Model

N_SAMPLES = 100
TEST_SIZE = 0.2


@pytest.fixture
def simple_dataset() -> Dataset:
    dataset = Dataset(name="test_dataset", targets=["y"], test_size=TEST_SIZE)

    @dataset.reader
    def reader(sample_frac: float = 1.0, random_state: int = 42) -> pd.DataFrame:
        rng = np.random.default_rng(17)
        frame = pd.DataFrame({"x1": rng.normal(size=N_SAMPLES), "x2": rng.normal(size=N_SAMPLES)})
        frame["y"] = (frame["x1"] + frame["x2"] > 0).astype(int)
        return frame.sample(frac=sample_frac, random_state=random_state)

    return dataset


@pytest.fixture
def sklearn_model(simple_dataset: Dataset) -> Model:
    from sklearn.linear_model import LogisticRegression

    model = Model(name="test_model", init=LogisticRegression, dataset=simple_dataset)

    @model.trainer
    def trainer(estimator: LogisticRegression, features: pd.DataFrame, target: pd.DataFrame) -> LogisticRegression:
        return estimator.fit(features, target.squeeze())

    @model.predictor
    def predictor(estimator: LogisticRegression, features: pd.DataFrame) -> List[float]:
        return [float(x) for x in estimator.predict(features)]

    @model.evaluator
    def evaluator(estimator: LogisticRegression, features: pd.DataFrame, target: pd.DataFrame) -> float:
        return float(estimator.score(features, target.squeeze()))

    return model
