"""Remote backend tests: deploy -> remote_train -> remote_predict against a tmp store
(the analog of the reference's Flyte-sandbox integration ring, test_flyte_remote.py,
but hermetic: the 'cluster' is the local subprocess executor)."""

import subprocess
import textwrap
from pathlib import Path

import pytest

from unionml_tpu.remote import BackendConfig, VersionFetchError, get_app_version

APP_SOURCE = textwrap.dedent(
    """
    from typing import List
    import numpy as np
    import pandas as pd
    from sklearn.linear_model import LogisticRegression
    from unionml_tpu import Dataset, Model

    dataset = Dataset(name="remote_dataset", targets=["y"], test_size=0.2)
    model = Model(name="remote_model", init=LogisticRegression, dataset=dataset)
    model.__app_module__ = "remote_app:model"

    @dataset.reader
    def reader(n: int = 100) -> pd.DataFrame:
        rng = np.random.default_rng(7)
        frame = pd.DataFrame({"x1": rng.normal(size=n), "x2": rng.normal(size=n)})
        frame["y"] = (frame["x1"] - frame["x2"] > 0).astype(int)
        return frame

    @model.trainer
    def trainer(estimator: LogisticRegression, features: pd.DataFrame, target: pd.DataFrame) -> LogisticRegression:
        return estimator.fit(features, target.squeeze())

    @model.predictor
    def predictor(estimator: LogisticRegression, features: pd.DataFrame) -> List[float]:
        return [float(x) for x in estimator.predict(features)]

    @model.evaluator
    def evaluator(estimator: LogisticRegression, features: pd.DataFrame, target: pd.DataFrame) -> float:
        return float(estimator.score(features, target.squeeze()))
    """
)


@pytest.fixture
def remote_app(tmp_path, monkeypatch):
    app_dir = tmp_path / "appsrc"
    app_dir.mkdir()
    (app_dir / "remote_app.py").write_text(APP_SOURCE)
    monkeypatch.syspath_prepend(str(app_dir))
    monkeypatch.chdir(app_dir)
    import importlib

    import remote_app

    importlib.reload(remote_app)
    remote_app.model.remote(backend_store=str(tmp_path / "store"))
    return remote_app


def test_deploy_and_train_and_predict(remote_app):
    model = remote_app.model
    version = model.remote_deploy(app_version="v1")
    assert version == "v1"

    artifact = model.remote_train(hyperparameters={"max_iter": 200}, wait=True)
    assert artifact is not None
    assert artifact.metrics["train"] > 0.8

    versions = model.remote_list_model_versions()
    assert len(versions) == 1

    preds = model.remote_predict(features=[{"x1": 2.0, "x2": -2.0}, {"x1": -2.0, "x2": 2.0}])
    assert preds == [1.0, 0.0]


def test_train_without_deploy_raises(remote_app):
    model = remote_app.model
    model.remote(backend_store=str(Path(model._backend.root).parent.parent / "empty_store"))
    with pytest.raises(RuntimeError, match="no deployed app versions"):
        model.remote_train(hyperparameters={"max_iter": 100})


def test_patch_deploy_suffixes_version(remote_app):
    model = remote_app.model
    model.remote_deploy(app_version="v1")
    # patch deploy with no explicit version derives one; requires git — give explicit
    version = model.remote_deploy(app_version="v1-patchabc", patch=True)
    assert version == "v1-patchabc"


def test_get_app_version_clean_and_dirty(tmp_path):
    repo = tmp_path / "repo"
    repo.mkdir()

    def git(*args):
        subprocess.run(["git", *args], cwd=repo, check=True, capture_output=True)

    git("init")
    git("config", "user.email", "t@t")
    git("config", "user.name", "t")
    (repo / "f.txt").write_text("hello")
    git("add", ".")
    git("commit", "-m", "init")

    sha = get_app_version(cwd=str(repo))
    assert len(sha) == 40

    (repo / "f.txt").write_text("dirty")
    with pytest.raises(VersionFetchError, match="uncommitted changes"):
        get_app_version(cwd=str(repo))
    assert get_app_version(allow_uncommitted=True, cwd=str(repo)) == sha


def test_failed_execution_surfaces_logs(remote_app):
    model = remote_app.model
    model.remote_deploy(app_version="v2")
    # a reader kwarg of the wrong kind makes the job fail inside the worker
    execution = model.remote_train(wait=False, hyperparameters={"max_iter": 100}, n="not-an-int")
    with pytest.raises(RuntimeError, match="FAILED"):
        model._backend.wait(execution)


def test_backend_config_store_path(tmp_path, monkeypatch):
    monkeypatch.setenv("UNIONML_TPU_STORE", str(tmp_path / "envstore"))
    config = BackendConfig(project="p", domain="d")
    assert str(config.store_path()).endswith("envstore/p/d")

def test_fault_injected_train_recovers_with_retries(remote_app, monkeypatch):
    """Slice-failure recovery: attempt 0 is hard-killed mid-run (no terminal status
    written), the watchdog marks it FAILED and resubmits; attempt 1 succeeds."""
    monkeypatch.setenv("UNIONML_TPU_FAULT_INJECT", "1")
    monkeypatch.setenv("UNIONML_TPU_HEARTBEAT_S", "0.2")
    model = remote_app.model
    model.remote_deploy(app_version="v3")
    execution = model.remote_train(wait=False, hyperparameters={"max_iter": 100})
    model._backend.wait(execution, retries=2)
    assert execution.status == "SUCCEEDED"
    assert execution.attempt == 1
    artifact = model._backend.fetch_artifact(model, execution)
    assert artifact.metrics["train"] > 0.8


def test_fault_without_retries_raises(remote_app, monkeypatch):
    monkeypatch.setenv("UNIONML_TPU_FAULT_INJECT", "5")
    model = remote_app.model
    model.remote_deploy(app_version="v4")
    execution = model.remote_train(wait=False, hyperparameters={"max_iter": 100})
    with pytest.raises(RuntimeError, match="FAILED"):
        model._backend.wait(execution, retries=0)
    assert execution.attempt == 0


def test_stale_heartbeat_marks_lost_and_resubmits(remote_app, monkeypatch):
    """Detached-handle watchdog: an execution stuck RUNNING with a stale heartbeat
    (the lost-slice case — no process handle to poll) is marked LOST and resubmitted."""
    import json as _json
    import time as _time

    monkeypatch.setenv("UNIONML_TPU_HEARTBEAT_S", "0.2")  # resubmitted worker beats fast
    model = remote_app.model
    model.remote_deploy(app_version="v5")
    execution = model.remote_train(wait=False, hyperparameters={"max_iter": 100})
    model._backend.wait(execution)  # let the real run finish

    # forge a lost state: RUNNING status + ancient heartbeat + no proc handle
    exec_dir = Path(execution.path)
    (exec_dir / "status").write_text("RUNNING")
    (exec_dir / "heartbeat").write_text(repr(_time.time() - 3600))
    from unionml_tpu.remote import Execution

    detached = Execution(id=execution.id, workflow=execution.workflow, path=execution.path)
    assert detached.heartbeat_age() > 3000
    model._backend.wait(detached, retries=2, heartbeat_timeout=1.0)
    assert detached.status == "SUCCEEDED"
    assert detached.attempt >= 1
    spec = _json.loads((exec_dir / "spec.json").read_text())
    assert spec["model_name"] == model.name


# ---------------------------------------------------------------- launcher seam


def test_slice_hosts_topology_table():
    from unionml_tpu.launcher import slice_hosts

    assert slice_hosts("v5e-8") == 1    # one v5e host carries 8 chips
    assert slice_hosts("v5e-16") == 2
    assert slice_hosts("v5litepod-32") == 4
    assert slice_hosts("v4-8") == 1     # v4 counts TensorCores: 8 cores = 4 chips
    assert slice_hosts("v4-32") == 4
    assert slice_hosts("v5p-16") == 2
    with pytest.raises(ValueError, match="unknown TPU generation"):
        slice_hosts("h100-8")
    with pytest.raises(ValueError, match="cannot parse"):
        slice_hosts("v5e")


def test_tpu_vm_launcher_provisions_through_interface(tmp_path, monkeypatch):
    """accelerator="v5e-8" provisions a slice through the Launcher interface: the
    injected provisioner sees the accelerator, the injected transport runs one
    worker per slice host — here executing the job_runner command locally, so the
    execution really trains end-to-end through the TPUVMLauncher path."""
    from unionml_tpu.launcher import TPUVMLauncher

    app_dir = tmp_path / "appsrc"
    app_dir.mkdir()
    (app_dir / "remote_app.py").write_text(APP_SOURCE)
    monkeypatch.syspath_prepend(str(app_dir))
    monkeypatch.chdir(app_dir)
    import importlib

    import remote_app

    importlib.reload(remote_app)
    model = remote_app.model

    provisioned = []
    transported = []

    def fake_provision(accelerator, execution_path):
        provisioned.append((accelerator, execution_path))
        return f"fake-node-{len(provisioned)}"

    def fake_transport(node, worker, command, env, log_path, log_mode):
        transported.append((node, worker))
        with open(log_path, log_mode) as log_file:
            return subprocess.Popen(command, env=env, stdout=log_file, stderr=subprocess.STDOUT)

    launcher = TPUVMLauncher(provisioner=fake_provision, transport=fake_transport)
    model.remote(
        backend_store=str(tmp_path / "store"), accelerator="v5e-8", launcher=launcher
    )
    model.remote_deploy(app_version="launcher-v1")
    artifact = model.remote_train(hyperparameters={"max_iter": 200}, wait=True)

    assert provisioned == [("v5e-8", provisioned[0][1])]
    assert "launcher-v1" not in provisioned[0][1]  # provisioner got the EXECUTION path
    assert transported == [("fake-node-1", 0)]  # v5e-8 = one host = one worker
    assert artifact.metrics["train"] > 0.8


def test_tpu_vm_launcher_sizes_workers_to_slice(tmp_path, monkeypatch):
    """With accelerator="v5e-16" (2 hosts) and default n_workers, the backend sizes
    the worker set to the slice topology and wires the jax.distributed env."""
    from unionml_tpu.launcher import LaunchSpec, TPUVMLauncher

    specs = []

    class Recorder(TPUVMLauncher):
        def launch(self, spec: LaunchSpec):
            specs.append(spec)

            class Done:
                returncode = 0

                def poll(self):
                    return 0

                def kill(self):
                    pass

                def wait(self):
                    return 0

            return [Done() for _ in spec.worker_envs]

    app_dir = tmp_path / "appsrc"
    app_dir.mkdir()
    (app_dir / "remote_app.py").write_text(APP_SOURCE)
    monkeypatch.syspath_prepend(str(app_dir))
    monkeypatch.chdir(app_dir)
    import importlib

    import remote_app

    importlib.reload(remote_app)
    model = remote_app.model
    model.remote(
        backend_store=str(tmp_path / "store"), accelerator="v5e-16", launcher=Recorder()
    )
    model.remote_deploy(app_version="sizing-v1")
    model.remote_train(wait=False)

    [spec] = specs
    assert spec.accelerator == "v5e-16"
    assert spec.n_workers == 2
    envs = spec.worker_envs
    assert envs[0]["UNIONML_TPU_PROCESS_ID"] == "0" and envs[1]["UNIONML_TPU_PROCESS_ID"] == "1"
    assert envs[0]["UNIONML_TPU_NUM_PROCESSES"] == "2"
    assert envs[0]["UNIONML_TPU_COORDINATOR"] == envs[1]["UNIONML_TPU_COORDINATOR"]


def test_tpu_vm_launcher_reuses_node_on_resubmit(tmp_path):
    """The watchdog's resubmit path relaunches the same execution; the launcher
    must reuse the provisioned slice, not try to create the node again."""
    from unionml_tpu.launcher import LaunchSpec, TPUVMLauncher

    provisions = []

    class Handle:
        returncode = 0

        def poll(self):
            return 0

        def kill(self):
            pass

        def wait(self):
            return 0

    launcher = TPUVMLauncher(
        provisioner=lambda acc, path: (provisions.append(acc), f"node-{len(provisions)}")[1],
        transport=lambda *a, **k: Handle(),
    )
    log = tmp_path / "logs.txt"
    spec = LaunchSpec(
        command=["echo", "hi"],
        worker_envs=[{}],
        log_paths=[log],
        log_mode="w",
        execution_path=str(tmp_path),
        accelerator="v5e-8",
    )
    launcher.launch(spec)
    launcher.launch(spec)  # resubmit
    assert provisions == ["v5e-8"]  # provisioned exactly once
    launcher.teardown(str(tmp_path))
    assert launcher._nodes == {}
