"""Template integrity tests: each shipped template renders and its app module imports
(decoration-time guards pass); the basic template additionally trains and predicts.
Analog of the reference's template-carried test suites
(templates/basic-aws-lambda/.../tests/unit/test_handler.py)."""

import importlib
import sys
from pathlib import Path

import pytest

from unionml_tpu.templating import list_templates, render_template


@pytest.fixture()
def render(tmp_path, monkeypatch):
    def _render(template: str):
        project = render_template(template, "rendered_app", tmp_path, git_init=False)
        monkeypatch.syspath_prepend(str(project))
        for mod in ("app", "handler"):
            sys.modules.pop(mod, None)
        return project

    yield _render
    for mod in ("app", "handler"):
        sys.modules.pop(mod, None)


@pytest.mark.parametrize("template", sorted(set(list_templates())))
def test_template_app_imports(render, template):
    render(template)
    module = importlib.import_module("app")
    assert module.model.name
    assert module.dataset._reader is not None


def test_basic_template_trains_and_predicts(render):
    render("basic")
    module = importlib.import_module("app")
    from sklearn.datasets import load_digits

    model_object, metrics = module.model.train(hyperparameters={"max_iter": 10000})
    assert metrics["train"] > 0.9
    sample = load_digits(as_frame=True).frame.sample(5, random_state=42)
    assert len(module.model.predict(features=sample)) == 5
