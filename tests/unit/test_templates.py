"""Template integrity tests: each shipped template renders and its app module imports
(decoration-time guards pass); the basic template additionally trains and predicts.
Analog of the reference's template-carried test suites
(templates/basic-aws-lambda/.../tests/unit/test_handler.py)."""

import importlib
import sys
from pathlib import Path

import pytest

from unionml_tpu.templating import list_templates, render_template


@pytest.fixture()
def render(tmp_path, monkeypatch):
    def _render(template: str):
        project = render_template(template, "rendered_app", tmp_path, git_init=False)
        monkeypatch.syspath_prepend(str(project))
        for mod in ("app", "handler"):
            sys.modules.pop(mod, None)
        return project

    yield _render
    for mod in ("app", "handler"):
        sys.modules.pop(mod, None)


@pytest.mark.parametrize("template", sorted(set(list_templates())))
def test_template_app_imports(render, template):
    render(template)
    module = importlib.import_module("app")
    assert module.model.name
    assert module.dataset._reader is not None


def test_basic_template_trains_and_predicts(render):
    render("basic")
    module = importlib.import_module("app")

    model_object, metrics = module.model.train(
        hyperparameters={"n_estimators": 50, "random_state": 0}
    )
    assert metrics["train"] > 0.9  # macro-F1
    sample = module.reader().drop(columns=[module.TARGET]).sample(5, random_state=42)
    predictions = module.model.predict(features=sample)
    assert len(predictions) == 5 and all(p in (0, 1, 2) for p in predictions)


def test_text_generation_template_trains_generates_and_serves(render, tmp_path):
    import asyncio
    import json

    render("text-generation")
    module = importlib.import_module("app")

    _, metrics = module.model.train(hyperparameters={"learning_rate": 3e-3})
    assert metrics["train"] < 3.0  # mean next-token cross-entropy (nats)
    prompts = ["the quick brown ", "a stitch "]
    outputs = module.model.predict(features=prompts)
    assert [t.startswith(p) for t, p in zip(outputs, prompts)] == [True, True]
    assert all(set(t[len(p):]) <= set(module.CHARS) for t, p in zip(outputs, prompts))
    assert module.model.predict(features=prompts) == outputs  # greedy determinism

    # artifact round trip: a reloaded LM generates the same continuations
    path = tmp_path / "model_object.ckpt"
    module.model.save(str(path))
    module.model.artifact = None
    module.model.load(str(path))
    assert module.model.predict(features=prompts) == outputs

    # generation over HTTP: prompt strings in, continuations out
    app = module.model.serve()
    status, texts, _ = asyncio.run(
        app.dispatch("POST", "/predict", json.dumps({"features": prompts}).encode())
    )
    assert status == 200 and texts == outputs

    # streaming route: ND-JSON chunks of per-prompt text pieces; reassembling
    # each prompt's pieces reproduces the non-streaming continuation
    async def consume():
        status, payload, content_type = await app.dispatch(
            "POST", "/predict-stream", json.dumps({"features": prompts}).encode()
        )
        assert status == 200 and content_type == "application/x-ndjson"
        return [chunk async for chunk in payload]

    chunks = asyncio.run(consume())
    assert len(chunks) > 1  # actually incremental, not one blob
    pieces = [json.loads(c.decode()) for c in chunks]
    for i, prompt in enumerate(prompts):
        assert prompt + "".join(p[i] for p in pieces) == outputs[i]

    # single-prompt streams ride the shared continuous-batching loop; two
    # CONCURRENT streaming requests must each reassemble to their own
    # non-streaming continuation (decode dispatches are shared, outputs exact)
    async def consume_one(prompt):
        status, payload, _ = await app.dispatch(
            "POST", "/predict-stream", json.dumps({"features": [prompt]}).encode()
        )
        assert status == 200
        parts = [json.loads(c.decode())[0] async for c in payload]
        return "".join(parts)

    async def concurrent():
        return await asyncio.gather(*(consume_one(p) for p in prompts))

    streamed = asyncio.run(concurrent())
    assert [p + s for p, s in zip(prompts, streamed)] == outputs
    # the cache stores (state, batcher): the strong state ref pins id reuse
    entry = module._continuous.get(id(module.model.artifact.model_object))
    assert entry is not None and entry[0] is module.model.artifact.model_object
    batcher = entry[1]
    assert batcher.decode_dispatches > 0

    # /metrics surfaces the shared batcher's utilization
    status, metrics_payload, _ = asyncio.run(app.dispatch("GET", "/metrics"))
    assert status == 200
    generation = metrics_payload["generation"]
    assert generation["slots"] == 4 and generation["decode_dispatches"] > 0
    assert generation["speculative"] is False
    # the template serves through the paged pool; occupancy is surfaced, and
    # with every stream drained the allocator must be balanced (a leak would
    # show as used > 0 — blocks release before each stream's end sentinel)
    kv = generation["kv_blocks"]
    assert kv["block_size"] == 16 and kv["used"] == 0

    # structured output: an '@<grammar> ' prefix constrains THAT request's
    # continuation by device-side token-DFA masking, on /predict and on the
    # continuously-batched single-prompt stream — and the two routes agree
    # token-exactly (greedy)
    import re

    g_prompt = "@word the quick brown "
    g_out = module.model.predict(features=[g_prompt, "plain "])
    cont = g_out[0][len("the quick brown ") :]
    assert cont and re.fullmatch(r"[a-z]+", cont), g_out[0]
    # an un-prefixed prompt decodes FREE, unaffected by its constrained
    # batchmate: equal to its solo free run, not merely prompt-prefixed
    assert g_out[1] == module.model.predict(features=["plain "])[0]
    streamed_word = asyncio.run(consume_one(g_prompt))
    assert "the quick brown " + streamed_word == g_out[0]

    # speculative decoding through the Generator façade: greedy-exact vs the
    # plain predictor (the half-depth draft changes speed, never tokens) —
    # including under a grammar, since the spec config shares the predictor's
    # constraint set and the DFA state threads along the draft's proposals
    spec = module.speculative_generator(module.model.artifact.model_object)
    spec_out = spec([module.encode(p) for p in prompts])
    assert [p + module.decode(r) for p, r in zip(prompts, spec_out)] == outputs
    word_gid, _ = module._split_grammar(g_prompt)  # the serving path's own mapping
    spec_word = spec([module.encode("the quick brown ")], constraint=word_gid)
    assert "the quick brown " + module.decode(spec_word[0]) == g_out[0]


def test_serverless_template_trains_and_scores(render):
    render("basic-serverless")
    module = importlib.import_module("app")

    _, metrics = module.model.train(hyperparameters={"alpha": 1e-4, "max_iter": 2000})
    assert metrics["test"] > 0.95  # ROC-AUC
    sample = module.reader(limit=4).drop(columns=["diagnosis"])
    probabilities = module.model.predict(features=sample)
    assert len(probabilities) == 4 and all(0.0 <= p <= 1.0 for p in probabilities)
