"""Beam search correctness.

Oracles: (1) with beam width covering the whole search space, beam search must
find the exact max-sum-log-prob continuation that brute-force enumeration of
every token sequence finds; (2) beam width 1 must equal greedy decoding."""

import itertools

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from unionml_tpu.models import GenerationConfig, Generator, Llama, LlamaConfig




def brute_force_best(module, params, prompt, steps, vocab):
    """Enumerate every continuation and return the max-sum-log-prob one."""
    best, best_score = None, -np.inf
    for cont in itertools.product(range(vocab), repeat=steps):
        tokens = list(prompt) + list(cont)
        logits = module.apply({"params": params}, jnp.asarray([tokens], jnp.int32))
        lp = jax.nn.log_softmax(logits[0].astype(jnp.float32), axis=-1)
        score = sum(float(lp[len(prompt) - 1 + i, cont[i]]) for i in range(steps))
        if score > best_score:
            best, best_score = cont, score
    return list(best), best_score


@pytest.mark.slow  # brute-force V^steps oracle, ~27s — outside the tier-1 budget
def test_full_width_beam_equals_exhaustive_search(micro_lm):
    module, params, config = micro_lm
    steps, vocab = 3, config.vocab_size
    gen = Generator(
        module, params, GenerationConfig(max_new_tokens=steps, temperature=0.0, prompt_buckets=(8,))
    )
    for prompt in ([1, 4, 2], [5, 3]):
        expected, _ = brute_force_best(module, params, prompt, steps, vocab)
        # beam width vocab^(steps-1) tracks every prefix -> exact search
        out = gen.beam_search([prompt], num_beams=vocab ** (steps - 1))
        assert out[0].tolist() == expected, prompt


def test_beam_one_equals_greedy(micro_lm):
    module, params, _ = micro_lm
    gen = Generator(
        module, params, GenerationConfig(max_new_tokens=8, temperature=0.0, prompt_buckets=(8,))
    )
    prompts = [[1, 2, 3], [4, 5]]
    np.testing.assert_array_equal(gen.beam_search(prompts, num_beams=1), gen(prompts))


def test_beam_width_improves_or_matches_score(micro_lm):
    """A wider beam can only find an equal-or-better-scoring sequence."""
    module, params, config = micro_lm
    steps = 4
    gen = Generator(
        module, params, GenerationConfig(max_new_tokens=steps, temperature=0.0, prompt_buckets=(8,))
    )
    prompt = [2, 1]

    def seq_score(cont):
        tokens = list(prompt) + list(cont)
        logits = module.apply({"params": params}, jnp.asarray([tokens], jnp.int32))
        lp = jax.nn.log_softmax(logits[0].astype(jnp.float32), axis=-1)
        return sum(float(lp[len(prompt) - 1 + i, cont[i]]) for i in range(steps))

    scores = [seq_score(gen.beam_search([prompt], num_beams=k)[0].tolist()) for k in (1, 2, 4, 8)]
    assert all(b >= a - 1e-5 for a, b in zip(scores, scores[1:])), scores


def test_beam_eos_finishes_and_pads(micro_lm):
    """Some eos choice must surface in its constrained run (tiny vocab: sweep
    them all), and everything after the first eos must be pad."""
    module, params, config = micro_lm
    seen_eos = False
    for eos in range(1, config.vocab_size):
        gen = Generator(
            module, params,
            GenerationConfig(max_new_tokens=6, temperature=0.0, prompt_buckets=(8,), eos_id=eos, pad_id=0),
        )
        out = gen.beam_search([[1, 2]], num_beams=3)[0].tolist()
        if eos in out:
            seen_eos = True
            cut = out.index(eos)
            assert all(t == 0 for t in out[cut + 1 :]), (eos, out)
    assert seen_eos  # the assertion body must have run for at least one eos
