"""Multi-tenant QoS (docs/serving.md "Multi-tenant QoS") + the OpenAI surface.

The pinned contracts:

- **buckets**: per-tenant req/s and generated-tokens/s token buckets shed 429
  with a ``Retry-After`` computed from the limiting bucket's actual refill
  time; anonymous traffic is never bucket-limited; the tenant state map is
  bounded (capacity + idle eviction — the TPU009 dogfood);
- **fairness**: waiting prompts admit deficit-round-robin across tenants
  within strict priority tiers — a hostile burst no longer FIFO-starves the
  other tenants, weights skew token share proportionally, zero-weight tenants
  are best-effort;
- **priority preemption**: a high-priority admission on a full paged engine
  preempts exactly one lowest-priority resident, and the victim's resumed
  stream is token-identical to an unpreempted run;
- **OpenAI compatibility**: ``POST /v1/completions`` (and chat) answer the
  OpenAI schema — ``stream=true`` SSE terminated by ``data: [DONE]``, correct
  ``usage`` counts — and unsupported params are clear 400s;
- **off = today's engine**: no registry + no headers leaves stats, metrics,
  and scheduling byte-for-byte unchanged.
"""

import asyncio
import json
import queue
import threading
import time
import types

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from unionml_tpu.models import GenerationConfig, Generator, Llama, LlamaConfig
from unionml_tpu.serving import ContinuousBatcher, ServingApp, TenantRegistry, TenantSpec
from unionml_tpu.serving.continuous import _Session
from unionml_tpu.serving.overload import QueueFullError, TenantThrottled
from unionml_tpu.serving.tenancy import (
    PRIORITIES,
    parse_priority,
    resolve_tenant,
    sanitize_tenant_id,
)


@pytest.fixture(scope="module")
def tiny():
    config = LlamaConfig.tiny(
        vocab_size=96, dim=64, n_layers=2, n_heads=4, n_kv_heads=2, hidden_dim=128,
        dtype=jnp.float32, param_dtype=jnp.float32,
    )
    module = Llama(config)
    params = module.init(jax.random.PRNGKey(1), jnp.zeros((1, 8), jnp.int32))["params"]
    return module, params


def _cfg(**overrides):
    kwargs = dict(max_new_tokens=8, temperature=0.0, prompt_buckets=(16,))
    kwargs.update(overrides)
    return GenerationConfig(**kwargs)


def _drain(stream):
    return [int(t) for chunk in stream for t in np.asarray(chunk).ravel()]


# ------------------------------------------------------------------ specs / identity


def test_tenant_spec_validation():
    TenantSpec(weight=0, req_per_s=0, tokens_per_s=0)  # all-zero is legal
    for bad in (
        dict(weight=-1), dict(req_per_s=-1), dict(tokens_per_s=-0.5),
        dict(burst_s=0), dict(priority="turbo"),
    ):
        with pytest.raises(ValueError):
            TenantSpec(**bad)


def test_parse_priority():
    assert parse_priority("high") == 0
    assert parse_priority(" Normal ") == 1
    assert parse_priority("BATCH") == 2
    with pytest.raises(ValueError):
        parse_priority("urgent")


def test_sanitize_tenant_id():
    assert sanitize_tenant_id("acme-1_2.3") == "acme-1_2.3"
    assert sanitize_tenant_id("evil\r\nX: 1") == "evilX1"
    assert sanitize_tenant_id(None) is None
    assert len(sanitize_tenant_id("x" * 500)) == 64


def test_resolve_tenant_precedence_and_key_digest():
    reg = TenantRegistry({"acme": TenantSpec()}, api_keys={"sk-secret-123": "acme"})
    assert resolve_tenant({"x-tenant-id": "beta"}, reg) == "beta"  # header wins
    assert resolve_tenant({"authorization": "Bearer sk-secret-123"}, reg) == "acme"
    derived = resolve_tenant({"authorization": "Bearer sk-unmapped-456"}, reg)
    # unmapped keys become stable digest-derived tenants; the secret itself
    # must never appear in the identity that reaches traces and metrics
    assert derived.startswith("key-") and "sk-unmapped-456" not in derived
    assert derived == resolve_tenant({"authorization": "Bearer sk-unmapped-456"}, None)
    assert resolve_tenant({}, reg) is None
    assert resolve_tenant({"authorization": "Basic Zm9v"}, reg) is None


# ------------------------------------------------------------------ buckets


def test_request_bucket_refill_and_retry_after():
    clk = [0.0]
    reg = TenantRegistry(
        {"t": TenantSpec(req_per_s=2.0, burst_s=1.0)}, clock=lambda: clk[0]
    )
    # cap = max(2*1, 1) = 2 requests of burst
    assert reg.try_admit("t") is None
    assert reg.try_admit("t") is None
    retry = reg.try_admit("t")
    assert retry == pytest.approx(0.5, rel=0.01)  # 1 token at 2/s
    clk[0] += 0.5
    assert reg.try_admit("t") is None  # refilled exactly one
    stats = reg.stats()["per_tenant"]["t"]
    assert stats["admitted"] == 3 and stats["shed"] == 1


def test_token_bucket_debt_blocks_new_admissions():
    clk = [0.0]
    reg = TenantRegistry(
        {"t": TenantSpec(tokens_per_s=10.0, burst_s=1.0)}, clock=lambda: clk[0]
    )
    assert reg.try_admit("t") is None
    reg.charge_tokens("t", 25)  # overdraw: 10 - 25 = -15
    retry = reg.try_admit("t")
    assert retry == pytest.approx(1.6, rel=0.01)  # (1 - (-15)) / 10
    clk[0] += 1.6
    assert reg.try_admit("t") is None


def test_anonymous_and_unlimited_tenants_never_shed():
    reg = TenantRegistry({"t": TenantSpec()})  # rates 0 = unlimited
    for _ in range(100):
        assert reg.try_admit(None) is None
        assert reg.try_admit("t") is None
    reg.charge_tokens(None, 10)  # no-op, no state minted for anonymous
    assert reg.stats()["per_tenant"].keys() == {"t"}


def test_registry_state_map_is_bounded():
    clk = [0.0]
    reg = TenantRegistry(max_tenants=4, idle_evict_s=100.0, clock=lambda: clk[0])
    for i in range(10):
        reg.try_admit(f"tenant-{i}")
    stats = reg.stats()
    assert stats["count"] <= 4 and stats["evicted"] >= 6
    # idle aging: the survivors evict once stale
    clk[0] += 101.0
    reg.try_admit("fresh")
    assert set(reg.stats()["per_tenant"]) == {"fresh"}


def test_registry_from_file_and_env_degrade(tmp_path, monkeypatch, caplog):
    path = tmp_path / "tenants.json"
    path.write_text(json.dumps({
        "default": {"req_per_s": 3},
        "tenants": {"acme": {"weight": 2, "priority": "high"}},
        "api_keys": {"sk-1": "acme"},
    }))
    reg = TenantRegistry.from_file(str(path))
    assert reg.weight("acme") == 2 and reg.default_priority("acme") == PRIORITIES["high"]
    assert reg.spec("unknown").req_per_s == 3
    assert reg.tenant_for_key("sk-1") == "acme"

    from unionml_tpu._logging import logger

    monkeypatch.setattr(logger, "propagate", True)
    monkeypatch.setenv("UNIONML_TPU_TENANT_CONFIG", str(tmp_path / "missing.json"))
    monkeypatch.setenv("UNIONML_TPU_DEFAULT_TENANT_RATE", "5")
    with caplog.at_level("WARNING", logger="unionml_tpu"):
        degraded = TenantRegistry.from_env()
    assert degraded is not None and degraded.default_spec.req_per_s == 5
    assert any("missing.json" in r.message for r in caplog.records)
    monkeypatch.delenv("UNIONML_TPU_TENANT_CONFIG")
    monkeypatch.delenv("UNIONML_TPU_DEFAULT_TENANT_RATE")
    assert TenantRegistry.from_env() is None  # neither knob set = tenancy off


# ------------------------------------------------------------------ DRR scheduling


def _queue_session(engine, prompt, tenant=None, priority=1):
    session = _Session(
        slot=-1, out=queue.Queue(), max_new=4, tenant=tenant, priority=priority,
        prompt=list(prompt),
    )
    engine._pending.append((list(prompt), session))
    return session


def _selection_order(engine, n):
    """Drain the waiting queue through the DRR selector, recording tenants."""
    order = []
    with engine._lock:
        for _ in range(n):
            engine._select_pending_locked()
            prompt, session = engine._pending.pop(0)
            order.append((session.tenant, session.priority))
    return order


def test_fifo_fast_path_without_qos(tiny):
    module, params = tiny
    engine = ContinuousBatcher(Generator(module, params, _cfg()), slots=1)
    try:
        for i in range(3):
            _queue_session(engine, [10 + i])
        with engine._lock:
            engine._drr_deficit["stale"] = 5.0
            engine._select_pending_locked()
            # FIFO order untouched, and the leftover per-tenant state evicted
            assert [p for p, _ in engine._pending] == [[10], [11], [12]]
            assert engine._drr_deficit == {}
    finally:
        engine.close()


def test_drr_interleaves_hostile_burst(tiny):
    module, params = tiny
    reg = TenantRegistry({"evil": TenantSpec(), "good": TenantSpec()})
    engine = ContinuousBatcher(Generator(module, params, _cfg()), slots=1, tenancy=reg)
    try:
        for _ in range(6):
            _queue_session(engine, [1] * 8, tenant="evil")
        for _ in range(2):
            _queue_session(engine, [2] * 8, tenant="good")
        order = [t for t, _ in _selection_order(engine, 8)]
        # FIFO would serve all 6 evil first; DRR must admit both good prompts
        # well before the hostile queue drains
        assert order.index("good") < 3
        assert {t for t in order[:5]} == {"evil", "good"}
    finally:
        engine.close()


def test_drr_weight_skews_share(tiny):
    module, params = tiny
    reg = TenantRegistry({"heavy": TenantSpec(weight=2), "light": TenantSpec(weight=1)})
    engine = ContinuousBatcher(Generator(module, params, _cfg()), slots=1, tenancy=reg)
    try:
        for _ in range(12):
            _queue_session(engine, [1] * 8, tenant="heavy")
            _queue_session(engine, [2] * 8, tenant="light")
        order = [t for t, _ in _selection_order(engine, 18)]
        heavy = order.count("heavy")
        light = order.count("light")
        # weight 2 vs 1: heavy's admitted share must be about double
        assert heavy / max(light, 1) == pytest.approx(2.0, rel=0.35), order
    finally:
        engine.close()


def test_zero_weight_tenant_is_best_effort(tiny):
    module, params = tiny
    reg = TenantRegistry({"burst": TenantSpec(weight=0), "paid": TenantSpec(weight=1)})
    engine = ContinuousBatcher(Generator(module, params, _cfg()), slots=1, tenancy=reg)
    try:
        for _ in range(3):
            _queue_session(engine, [1] * 4, tenant="burst")
        for _ in range(3):
            _queue_session(engine, [2] * 4, tenant="paid")
        order = [t for t, _ in _selection_order(engine, 6)]
        # every weighted admission lands before any best-effort one
        assert order == ["paid"] * 3 + ["burst"] * 3
    finally:
        engine.close()


def test_priority_tiers_are_strict(tiny):
    module, params = tiny
    reg = TenantRegistry()
    engine = ContinuousBatcher(Generator(module, params, _cfg()), slots=1, tenancy=reg)
    try:
        _queue_session(engine, [1] * 4, tenant="a", priority=2)  # batch
        _queue_session(engine, [2] * 4, tenant="b", priority=1)  # normal
        _queue_session(engine, [3] * 4, tenant="c", priority=0)  # high
        order = _selection_order(engine, 3)
        assert [p for _, p in order] == [0, 1, 2]
    finally:
        engine.close()


def test_submit_priority_validation_and_string_tier(tiny):
    module, params = tiny
    engine = ContinuousBatcher(Generator(module, params, _cfg()), slots=1)
    try:
        out = _drain(engine.submit([3, 1, 4], priority="batch"))
        assert len(out) == 8
        with pytest.raises(ValueError):
            engine.submit([3, 1, 4], priority=7)
        with pytest.raises(ValueError):
            engine.submit([3, 1, 4], priority="turbo")
    finally:
        engine.close()


# ------------------------------------------------------------------ bucket sheds at the engine


def test_engine_sheds_tenant_over_rate_with_retry_after(tiny):
    module, params = tiny
    clk = [0.0]
    reg = TenantRegistry(
        {"slow": TenantSpec(req_per_s=0.5, burst_s=2.0)}, clock=lambda: clk[0]
    )
    engine = ContinuousBatcher(Generator(module, params, _cfg()), slots=2, tenancy=reg)
    try:
        _drain(engine.submit([3, 1, 4], tenant="slow"))
        with pytest.raises(TenantThrottled) as exc_info:
            engine.submit([3, 1, 4], tenant="slow")
        assert exc_info.value.retry_after_s == pytest.approx(2.0, rel=0.01)
        assert exc_info.value.tenant == "slow"
        assert isinstance(exc_info.value, QueueFullError)  # rides the 429 path
        assert engine.stats()["tenancy"]["shed_tenant_limit"] == 1
        # anonymous traffic rides through the same engine unlimited
        assert len(_drain(engine.submit([3, 1, 4]))) == 8
    finally:
        engine.close()


def test_stats_off_contract(tiny):
    module, params = tiny
    engine = ContinuousBatcher(Generator(module, params, _cfg()), slots=1)
    try:
        _drain(engine.submit([3, 1, 4]))
        assert "tenancy" not in engine.stats()
        assert engine.tenant_census() == {}
    finally:
        engine.close()


def test_tenant_census_counts_live_streams(tiny):
    module, params = tiny
    engine = ContinuousBatcher(Generator(module, params, _cfg()), slots=1)
    try:
        _queue_session(engine, [1] * 4, tenant="a")
        _queue_session(engine, [2] * 4, tenant="a")
        _queue_session(engine, [3] * 4, tenant="b")
        _queue_session(engine, [4] * 4)  # anonymous: omitted
        census = engine.tenant_census()
        assert census == {
            "a": {"resident": 0, "waiting": 2},
            "b": {"resident": 0, "waiting": 1},
        }
        from unionml_tpu.observability.health import fleet_debug

        debug = fleet_debug(engine)
        assert debug["tenants"]["a"]["waiting"] == 2
        with engine._lock:
            engine._pending.clear()
    finally:
        engine.close()


# ------------------------------------------------------------------ priority preemption


def _slow_decode(engine, dispatch_s=0.02):
    real = engine.gen._decode

    def slow(*args, _real=real, **kwargs):
        time.sleep(dispatch_s)
        return _real(*args, **kwargs)

    engine.gen._decode = slow


def test_high_priority_preempts_exactly_one_lowest_priority_resident(tiny):
    module, params = tiny
    cfg = _cfg(max_new_tokens=32)
    gen = Generator(module, params, cfg)
    reference = {
        tuple(p): list(map(int, gen([p])[0]))
        for p in ([3, 1, 4, 1, 5], [9, 2, 6, 5], [7, 7, 1])
    }
    engine = ContinuousBatcher(gen, slots=2, decode_chunk=2, block_size=16, pool_blocks=24)
    try:
        engine.warmup()
        _slow_decode(engine)
        results = {}

        def consume(name, stream):
            results[name] = _drain(stream)

        normal = engine.submit([3, 1, 4, 1, 5], priority=1)
        batch = engine.submit([9, 2, 6, 5], priority=2)
        threads = [
            threading.Thread(target=consume, args=("normal", normal)),
            threading.Thread(target=consume, args=("batch", batch)),
        ]
        for t in threads:
            t.start()
        # wait until both residents hold the engine's two slots
        deadline = time.monotonic() + 5.0
        while engine.occupancy()[0] < 2 and time.monotonic() < deadline:
            time.sleep(0.005)
        high = engine.submit([7, 7, 1], priority=0)
        high_out = _drain(high)
        for t in threads:
            t.join()
        # exactly one preemption, and the BATCH resident was the victim
        assert engine.priority_preemptions == 1
        assert engine.preemptions == 1
        assert engine.stats()["tenancy"]["priority_preemptions"] == 1
        # the preempted stream resumed token-identically; nobody truncated
        assert high_out == reference[(7, 7, 1)]
        assert results["batch"] == reference[(9, 2, 6, 5)]
        assert results["normal"] == reference[(3, 1, 4, 1, 5)]
    finally:
        engine.close()


def test_no_priority_preemption_without_lower_priority_residents(tiny):
    module, params = tiny
    cfg = _cfg(max_new_tokens=16)
    gen = Generator(module, params, cfg)
    engine = ContinuousBatcher(gen, slots=1, decode_chunk=2, block_size=16, pool_blocks=12)
    try:
        engine.warmup()
        _slow_decode(engine)
        results = {}

        def consume(name, stream):
            results[name] = _drain(stream)

        first = engine.submit([3, 1, 4], priority=0)  # high resident
        thread = threading.Thread(target=consume, args=("first", first))
        thread.start()
        deadline = time.monotonic() + 5.0
        while engine.occupancy()[0] < 1 and time.monotonic() < deadline:
            time.sleep(0.005)
        # an equal-priority arrival WAITS (no preemption among peers)
        second = engine.submit([9, 2], priority=0)
        results["second"] = _drain(second)
        thread.join()
        assert engine.priority_preemptions == 0
        assert len(results["first"]) == 16 and len(results["second"]) == 16
    finally:
        engine.close()


# ------------------------------------------------------------------ HTTP layer


def _app(tiny, cfg=None, tenancy=None, tokenizer=None, **engine_kwargs):
    module, params = tiny
    engine = ContinuousBatcher(
        Generator(module, params, cfg or _cfg()), slots=2, tenancy=tenancy,
        **engine_kwargs,
    )
    model = types.SimpleNamespace(
        artifact=object(), generation_batcher=engine, _predictor_config=None,
        _compiled_predictor=None, _stream_predictor=None, name="tiny",
    )
    if tokenizer is not None:
        model.tokenizer = tokenizer
    app = ServingApp(model)
    app._started = True
    return app, engine


def _dispatch(app, method, path, body=b"", headers=None):
    return asyncio.run(app.server.dispatch_with_headers(method, path, body, headers))


def _dispatch_stream(app, method, path, body=b"", headers=None):
    """Dispatch AND drain a streaming payload inside one event loop (the
    stream generator schedules executor work on the loop it was created in)."""

    async def run():
        status, payload, ct, extra = await app.server.dispatch_with_headers(
            method, path, body, headers
        )
        if hasattr(payload, "__aiter__"):
            payload = [chunk async for chunk in payload]
        return status, payload, ct, extra

    return asyncio.run(run())


def test_http_tenant_shed_is_distinct_and_carries_refill_retry_after(tiny):
    reg = TenantRegistry({"slow": TenantSpec(req_per_s=0.01, burst_s=100.0)})
    app, engine = _app(tiny, tenancy=reg)
    try:
        body = json.dumps({"prompt": [3, 1, 4], "max_tokens": 2}).encode()
        status, _, _, _ = _dispatch(
            app, "POST", "/v1/completions", body, {"x-tenant-id": "slow"}
        )
        assert status == 200
        status, payload, _, extra = _dispatch(
            app, "POST", "/v1/completions", body, {"x-tenant-id": "slow"}
        )
        assert status == 429
        # Retry-After from the bucket's refill (1 token at 0.01/s = ~100s
        # minus whatever wall clock the first request consumed), not the
        # server's fixed 1s hint
        assert 50.0 < float(extra["Retry-After"]) <= 100.0
        overload = app.metrics.snapshot()["overload"]
        assert overload.get("shed_tenant_limit") == 1
        assert "shed_queue_full" not in overload
    finally:
        engine.close()


def test_http_invalid_priority_is_400(tiny):
    app, engine = _app(tiny)
    try:
        status, payload, _, _ = _dispatch(
            app, "POST", "/v1/completions",
            json.dumps({"prompt": [3]}).encode(), {"x-priority": "turbo"},
        )
        assert status == 400 and "priority" in payload["detail"]
    finally:
        engine.close()


def test_trace_carries_tenant_and_debug_filter(tiny):
    app, engine = _app(tiny)
    app.configure_observability(trace=True, access_log=False)
    try:
        body = json.dumps({"prompt": [3, 1, 4], "max_tokens": 2}).encode()
        _dispatch(app, "POST", "/v1/completions", body,
                  {"x-tenant-id": "acme", "x-priority": "high"})
        _dispatch(app, "POST", "/v1/completions", body, {"x-tenant-id": "beta"})
        _dispatch(app, "POST", "/v1/completions", body)  # anonymous
        status, snap, _, _ = _dispatch(app, "GET", "/debug/requests?tenant=acme")
        assert status == 200
        entries = snap["completed"]
        assert len(entries) == 1
        assert entries[0]["tenant"] == "acme" and entries[0]["priority"] == "high"
        status, snap, _, _ = _dispatch(app, "GET", "/debug/requests")
        tenants = [e.get("tenant") for e in snap["completed"]]
        assert set(tenants) == {"acme", "beta", None}
    finally:
        engine.close()


def test_metrics_tenants_section_gated_on_registry(tiny):
    reg = TenantRegistry({"acme": TenantSpec(weight=2)})
    app, engine = _app(tiny, tenancy=reg)
    app.tenancy = reg  # the app surface mirrors what serve would install
    try:
        body = json.dumps({"prompt": [3, 1, 4], "max_tokens": 2}).encode()
        _dispatch(app, "POST", "/v1/completions", body, {"x-tenant-id": "acme"})
        status, snapshot, _, _ = _dispatch(app, "GET", "/metrics")
        assert snapshot["tenants"]["per_tenant"]["acme"]["admitted"] == 1
        assert snapshot["tenants"]["per_tenant"]["acme"]["generated_tokens"] == 2
        # the same snapshot renders as Prometheus exposition without error
        status, text, ct, _ = _dispatch(app, "GET", "/metrics?format=prometheus")
        assert status == 200 and "tenants" in text
    finally:
        engine.close()


def test_metrics_without_registry_unchanged(tiny):
    app, engine = _app(tiny)
    try:
        status, snapshot, _, _ = _dispatch(app, "GET", "/metrics")
        assert "tenants" not in snapshot
    finally:
        engine.close()


# ------------------------------------------------------------------ OpenAI surface


def test_openai_completion_usage_and_schema(tiny):
    app, engine = _app(tiny)
    try:
        status, payload, ct, _ = _dispatch(
            app, "POST", "/v1/completions",
            json.dumps({"prompt": [3, 1, 4, 1, 5], "max_tokens": 4, "model": "m1"}).encode(),
        )
        assert status == 200 and ct == "application/json"
        assert payload["object"] == "text_completion" and payload["model"] == "m1"
        assert payload["id"].startswith("cmpl-")
        choice = payload["choices"][0]
        assert choice["finish_reason"] == "length" and choice["index"] == 0
        assert payload["usage"] == {
            "prompt_tokens": 5, "completion_tokens": 4, "total_tokens": 9,
        }
        # no tokenizer: text is the documented space-joined token-id fallback
        assert len(choice["text"].split()) == 4
    finally:
        engine.close()


def test_openai_stream_sse_framing_and_done(tiny):
    app, engine = _app(tiny)
    try:
        status, chunks, ct, _ = _dispatch_stream(
            app, "POST", "/v1/completions",
            json.dumps({"prompt": [3, 1, 4], "max_tokens": 5, "stream": True}).encode(),
        )
        assert status == 200 and ct == "text/event-stream"
        assert all(chunk.startswith(b"data: ") and chunk.endswith(b"\n\n") for chunk in chunks)
        assert chunks[-1] == b"data: [DONE]\n\n"
        events = [json.loads(chunk[6:]) for chunk in chunks[:-1]]
        assert all(e["object"] == "text_completion" for e in events)
        # every event before the last streams text with no finish_reason; the
        # final event carries finish_reason + usage
        assert all(e["choices"][0]["finish_reason"] is None for e in events[:-1])
        final = events[-1]
        assert final["choices"][0]["finish_reason"] in ("stop", "length")
        emitted = final["usage"]["completion_tokens"]
        assert emitted == 5 and final["usage"]["prompt_tokens"] == 3
        streamed = sum(len(e["choices"][0]["text"].split()) for e in events[:-1])
        assert streamed == emitted
    finally:
        engine.close()


def test_openai_chat_with_tokenizer(tiny):
    class Tok:
        def encode(self, text):
            return [1 + (ord(c) % 90) for c in text][:12]

        def decode(self, ids):
            return "".join(chr(97 + (i % 26)) for i in ids)

    app, engine = _app(tiny, tokenizer=Tok())
    try:
        status, payload, _, _ = _dispatch(
            app, "POST", "/v1/chat/completions",
            json.dumps({
                "messages": [{"role": "user", "content": "hi"}], "max_tokens": 3,
            }).encode(),
        )
        assert status == 200 and payload["object"] == "chat.completion"
        message = payload["choices"][0]["message"]
        assert message["role"] == "assistant" and isinstance(message["content"], str)
        assert payload["usage"]["completion_tokens"] == 3

        status, chunks, ct, _ = _dispatch_stream(
            app, "POST", "/v1/chat/completions",
            json.dumps({
                "messages": [{"role": "user", "content": "hi"}],
                "max_tokens": 2, "stream": True,
            }).encode(),
        )
        assert status == 200 and ct == "text/event-stream"
        events = [json.loads(chunk[6:]) for chunk in chunks[:-1]]
        assert events[0]["choices"][0]["delta"] == {"role": "assistant"}
        assert chunks[-1] == b"data: [DONE]\n\n"
    finally:
        engine.close()


def test_openai_rejections(tiny):
    app, engine = _app(tiny)
    try:
        cases = [
            ({"prompt": "text prompt"}, "tokenizer"),
            ({"prompt": [1, 2], "n": 3}, "n"),
            # stop=/logprobs are SUPPORTED now (docs/workloads.md PR); their
            # happy paths and validation live in tests/unit/test_workloads.py
            ({"prompt": [1, 2], "echo": True}, "echo"),
            ({"prompt": [1, 2], "max_tokens": 0}, "max_tokens"),
            ({"prompt": []}, "non-empty"),
            ({"prompt": ["a", "b"]}, "token ids"),
            ({}, "prompt"),
            ({"messages": []}, None),  # chat needs messages
        ]
        for body, needle in cases[:-1]:
            status, payload, _, _ = _dispatch(
                app, "POST", "/v1/completions", json.dumps(body).encode()
            )
            assert status == 400, (body, payload)
            if needle:
                assert needle in payload["detail"], (body, payload)
        status, payload, _, _ = _dispatch(
            app, "POST", "/v1/chat/completions", json.dumps({"messages": []}).encode()
        )
        assert status == 400
    finally:
        engine.close()


def test_openai_404_without_generation_engine():
    model = types.SimpleNamespace(
        artifact=object(), _predictor_config=None, _compiled_predictor=None,
        _stream_predictor=None, name="none",
    )
    app = ServingApp(model)
    app._started = True
    status, payload, _, _ = _dispatch(
        app, "POST", "/v1/completions", json.dumps({"prompt": [1]}).encode()
    )
    assert status == 404 and "generation" in payload["detail"]
    status, payload, _, _ = _dispatch(app, "GET", "/v1/models")
    assert status == 200 and payload["data"][0]["id"] == "none"


def test_openai_max_tokens_clipped_to_engine_budget(tiny):
    app, engine = _app(tiny)  # budget 8
    try:
        status, payload, _, _ = _dispatch(
            app, "POST", "/v1/completions",
            json.dumps({"prompt": [3, 1, 4], "max_tokens": 4096}).encode(),
        )
        assert status == 200
        assert payload["usage"]["completion_tokens"] == 8
    finally:
        engine.close()
