"""Persistent XLA compilation cache wiring (unionml_tpu/compile_cache.py)."""

import os

import jax
import pytest

from unionml_tpu import enable_compile_cache
from unionml_tpu.compile_cache import _maybe_enable_from_env


@pytest.fixture(autouse=True)
def restore_jax_cache_config():
    """These tests mutate process-global JAX config; later tests in the same
    pytest process must not inherit a cache dir pointing at a deleted tmpdir."""
    cache_dir = jax.config.jax_compilation_cache_dir
    min_secs = jax.config.jax_persistent_cache_min_compile_time_secs
    yield
    jax.config.update("jax_compilation_cache_dir", cache_dir)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", min_secs)


def test_enable_sets_jax_config_and_creates_dir(tmp_path):
    target = tmp_path / "xla-cache"
    resolved = enable_compile_cache(str(target))
    assert resolved == str(target)
    assert target.is_dir()
    assert jax.config.jax_compilation_cache_dir == str(target)


def test_env_flag_uses_default_location(tmp_path, monkeypatch):
    # "1" means "on, default location"; point HOME at tmp so the default
    # expands under the test sandbox
    monkeypatch.setenv("HOME", str(tmp_path))
    monkeypatch.setenv("UNIONML_TPU_COMPILE_CACHE", "1")
    resolved = enable_compile_cache()
    assert resolved == str(tmp_path / ".cache" / "unionml_tpu" / "xla")
    assert os.path.isdir(resolved)


def test_env_path_wins_and_import_hook_applies_it(tmp_path, monkeypatch):
    target = tmp_path / "from-env"
    monkeypatch.setenv("UNIONML_TPU_COMPILE_CACHE", str(target))
    _maybe_enable_from_env()
    assert jax.config.jax_compilation_cache_dir == str(target)
    assert target.is_dir()


def test_import_hook_respects_off_flags(monkeypatch):
    # inherited-env opt-out: a child of the benchmark suite can disable the
    # cache with =0 without the value being mistaken for a directory path
    for off in ("0", "false", "no", "off"):
        monkeypatch.setenv("UNIONML_TPU_COMPILE_CACHE", off)
        before = jax.config.jax_compilation_cache_dir
        _maybe_enable_from_env()
        assert jax.config.jax_compilation_cache_dir == before
        assert not os.path.exists(off)


def test_jitted_program_lands_in_the_cache(tmp_path):
    """End-to-end: compiling under the cache writes an entry (CPU backend
    serializes executables, so this exercises the real write path)."""
    import jax.numpy as jnp

    target = tmp_path / "cache-e2e"
    enable_compile_cache(str(target))
    # force caching of even sub-second compiles for the test
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)

    @jax.jit
    def f(x):
        return (x @ x.T).sum()

    f(jnp.ones((64, 64))).block_until_ready()
    entries = list(target.iterdir())
    assert entries, "no cache entry written"
