"""GKE manifest emitter: LaunchSpec -> kubectl-applyable Indexed Job + Service.

Pure-function ring for :mod:`unionml_tpu.gke` (no cluster, no shim): topology
mapping, the Indexed-Job/coordinator-DNS/completion-index contract multi-host
jax.distributed needs, TPU chip limits, and the store-volume shapes. The
kubectl-shim e2e lives in tests/integration/test_gke.py.
"""

from pathlib import Path

import pytest

from unionml_tpu.gke import gke_accelerator_type, gke_job_manifest, gke_topology
from unionml_tpu.launcher import LaunchSpec


def make_spec(n_workers=2, accelerator="v5e-16", image="gcr.io/p/app:v1", **overrides):
    envs = []
    for worker in range(n_workers):
        env = {
            "PYTHONPATH": "/store/bundle:/repo",
            "UNIONML_TPU_NUM_PROCESSES": str(n_workers),
            "UNIONML_TPU_COORDINATOR": "127.0.0.1:43210",
            "UNIONML_TPU_PROCESS_ID": str(worker),
            "JAX_PLATFORMS": "tpu",
            "HOME": "/root",  # must NOT leak into the pod env
        }
        envs.append(env)
    kwargs = dict(
        command=["python", "-m", "unionml_tpu.job_runner", "/store/executions/m/e1"],
        worker_envs=envs,
        log_paths=[Path(f"/tmp/logs.{i}.txt") for i in range(n_workers)],
        log_mode="w",
        execution_path="/store/executions/m/e1",
        accelerator=accelerator,
        image=image,
        store_root="/store",
    )
    kwargs.update(overrides)
    return LaunchSpec(**kwargs)


def job_of(manifest):
    return next(i for i in manifest["items"] if i["kind"] == "Job")


def pod_of(manifest):
    return job_of(manifest)["spec"]["template"]["spec"]


class TestTopologyMapping:
    def test_accelerator_types(self):
        assert gke_accelerator_type("v5e-8") == "tpu-v5-lite-podslice"
        assert gke_accelerator_type("v6e-4") == "tpu-v6e-slice"
        assert gke_accelerator_type("v4-32") == "tpu-v4-podslice"
        assert gke_accelerator_type("v5p-16") == "tpu-v5p-slice"

    def test_2d_topologies(self):
        assert gke_topology("v5e-1") == "1x1"
        assert gke_topology("v5e-8") == "2x4"
        assert gke_topology("v5e-16") == "4x4"
        assert gke_topology("v6e-256") == "16x16"

    def test_3d_generations_require_explicit_topology(self):
        with pytest.raises(ValueError, match="topology="):
            gke_topology("v4-32")
        # ...but the manifest accepts one
        manifest = gke_job_manifest(make_spec(n_workers=4, accelerator="v4-32"), topology="2x2x4")
        assert pod_of(manifest)["nodeSelector"]["cloud.google.com/gke-tpu-topology"] == "2x2x4"

    def test_unknown_shapes_rejected(self):
        with pytest.raises(ValueError, match="cannot parse"):
            gke_topology("v5e")
        with pytest.raises(ValueError, match="unknown TPU generation"):
            gke_topology("h100-8")
        with pytest.raises(ValueError, match="no standard 2D topology"):
            gke_topology("v5e-12")


class TestManifestShape:
    def test_indexed_job_with_headless_service(self):
        manifest = gke_job_manifest(make_spec())
        kinds = [i["kind"] for i in manifest["items"]]
        assert kinds == ["Service", "Job"]
        svc, job = manifest["items"]
        assert svc["spec"]["clusterIP"] == "None"
        name = job["metadata"]["name"]
        assert svc["spec"]["selector"] == {"job-name": name}
        assert job["spec"]["completionMode"] == "Indexed"
        assert job["spec"]["completions"] == 2 and job["spec"]["parallelism"] == 2
        # retries belong to the backend watchdog, not kubelet/the job controller
        assert job["spec"]["backoffLimit"] == 0
        # terminal jobs linger for inspection; the cluster GCs them after a day
        assert job["spec"]["ttlSecondsAfterFinished"] == 86400
        assert pod_of(manifest)["restartPolicy"] == "Never"
        assert pod_of(manifest)["subdomain"] == name

    def test_job_name_is_per_attempt(self):
        first = job_of(gke_job_manifest(make_spec()))["metadata"]["name"]
        retry = job_of(gke_job_manifest(make_spec(attempt=1)))["metadata"]["name"]
        assert first != retry and first.endswith("-a0") and retry.endswith("-a1")

    def test_tpu_node_selectors_and_chip_limits(self):
        manifest = gke_job_manifest(make_spec())  # v5e-16: 2 hosts x 8 chips
        pod = pod_of(manifest)
        assert pod["nodeSelector"]["cloud.google.com/gke-tpu-accelerator"] == "tpu-v5-lite-podslice"
        assert pod["nodeSelector"]["cloud.google.com/gke-tpu-topology"] == "4x4"
        assert pod["containers"][0]["resources"]["limits"]["google.com/tpu"] == 8

    def test_extra_node_selectors_merge(self):
        manifest = gke_job_manifest(make_spec(), node_selector={"cloud.google.com/gke-spot": "true"})
        assert pod_of(manifest)["nodeSelector"]["cloud.google.com/gke-spot"] == "true"

    def test_entrypoint_args_are_the_execution_path(self):
        container = pod_of(gke_job_manifest(make_spec()))["containers"][0]
        assert container["image"] == "gcr.io/p/app:v1"
        # image entrypoint is `python -m unionml_tpu.job_runner` (container.py)
        assert container["args"] == ["/store/executions/m/e1"]


class TestWorkerEnv:
    def env_by_name(self, manifest):
        return {e["name"]: e for e in pod_of(manifest)["containers"][0]["env"]}

    def test_coordinator_rewritten_to_pod0_dns(self):
        manifest = gke_job_manifest(make_spec())
        env = self.env_by_name(manifest)
        job = job_of(manifest)["metadata"]["name"]
        # loopback coordinator is meaningless across pods; port is preserved
        assert env["UNIONML_TPU_COORDINATOR"]["value"] == f"{job}-0.{job}:43210"

    def test_process_id_from_completion_index(self):
        env = self.env_by_name(gke_job_manifest(make_spec()))
        field = env["UNIONML_TPU_PROCESS_ID"]["valueFrom"]["fieldRef"]["fieldPath"]
        assert field == "metadata.annotations['batch.kubernetes.io/job-completion-index']"

    def test_only_framework_env_forwarded(self):
        env = self.env_by_name(gke_job_manifest(make_spec()))
        assert "HOME" not in env
        assert env["JAX_PLATFORMS"]["value"] == "tpu"
        assert env["UNIONML_TPU_NUM_PROCESSES"]["value"] == "2"

    def test_single_worker_has_no_service(self):
        # the Service exists solely for the multi-host coordinator DNS name;
        # single-host slices must not leak one per execution
        spec = make_spec(n_workers=1, accelerator="v5e-8")
        manifest = gke_job_manifest(spec)
        assert [i["kind"] for i in manifest["items"]] == ["Job"]

    def test_single_worker_has_no_distributed_env(self):
        spec = make_spec(n_workers=1, accelerator="v5e-8")
        for env in spec.worker_envs:
            env.pop("UNIONML_TPU_COORDINATOR")
            env.pop("UNIONML_TPU_PROCESS_ID")
            env.pop("UNIONML_TPU_NUM_PROCESSES")
        env = self.env_by_name(gke_job_manifest(spec))
        assert "UNIONML_TPU_COORDINATOR" not in env
        assert "UNIONML_TPU_PROCESS_ID" not in env


class TestVolumesAndErrors:
    def test_store_mounted_hostpath_by_default(self):
        pod = pod_of(gke_job_manifest(make_spec()))
        assert pod["volumes"] == [
            {"name": "store", "hostPath": {"path": "/store", "type": "DirectoryOrCreate"}}
        ]
        # same path inside the pod: execution dirs resolve without translation
        assert pod["containers"][0]["volumeMounts"] == [{"name": "store", "mountPath": "/store"}]

    def test_store_claim_mounts_pvc(self):
        pod = pod_of(gke_job_manifest(make_spec(), store_claim="unionml-store"))
        assert pod["volumes"] == [
            {"name": "store", "persistentVolumeClaim": {"claimName": "unionml-store"}}
        ]

    def test_service_account(self):
        pod = pod_of(gke_job_manifest(make_spec(), service_account="tpu-sa"))
        assert pod["serviceAccountName"] == "tpu-sa"

    def test_image_required_with_override(self):
        with pytest.raises(ValueError, match="image"):
            gke_job_manifest(make_spec(image=None))
        manifest = gke_job_manifest(make_spec(image=None), image="local/app:dev")
        assert pod_of(manifest)["containers"][0]["image"] == "local/app:dev"

    def test_accelerator_required(self):
        with pytest.raises(ValueError, match="accelerator"):
            gke_job_manifest(make_spec(accelerator=None))
