"""Per-framework app e2e: torch and keras through the full Model protocol.

The reference treats sklearn/pytorch/keras as co-equal first-class trainers
(tests/integration/{pytorch,keras}_app/quickstart.py run through serving in
test_fastapi.py; default saver/loader branches unionml/model.py:931-988). The
sklearn ring lives in test_model.py/test_serving.py; this module covers the
other two: train -> predict -> save -> load -> identical predictions -> serve.
"""

import asyncio
import json
from typing import List

import numpy as np
import pandas as pd
import pytest

from unionml_tpu import Dataset, Model

N, DIM = 120, 4


def _frame(seed: int = 0) -> pd.DataFrame:
    rng = np.random.default_rng(seed)
    weights = rng.normal(size=DIM)
    X = rng.normal(size=(N, DIM)).astype("float32")
    frame = pd.DataFrame(X, columns=[f"x{i}" for i in range(DIM)])
    frame["y"] = (X @ weights > 0).astype("int64")
    return frame


def _roundtrip_and_serve(model: Model, tmp_path, hyperparameters=None, artifact_name="artifact.bin"):
    """Shared drive: train, predict, save/load round trip, HTTP dispatch."""
    _, metrics = model.train(hyperparameters=hyperparameters)
    assert metrics["train"] > 0.8, metrics

    records = _frame().drop(columns=["y"]).head(5).to_dict("records")
    before = model.predict(features=records)
    assert len(before) == 5

    path = tmp_path / artifact_name
    model.save(str(path))
    model.artifact = None
    model.load(str(path))
    assert model.predict(features=records) == before

    app = model.serve()
    status, preds, _ = asyncio.run(
        app.dispatch("POST", "/predict", json.dumps({"features": records}).encode())
    )
    assert status == 200 and preds == before


def test_torch_app_end_to_end(tmp_path):
    torch = pytest.importorskip("torch")

    dataset = Dataset(name="torch_ds", targets=["y"], test_size=0.25)

    class Net(torch.nn.Module):
        def __init__(self, hidden: int = 16):
            super().__init__()
            self.hidden = hidden
            self.layers = torch.nn.Sequential(
                torch.nn.Linear(DIM, hidden), torch.nn.ReLU(), torch.nn.Linear(hidden, 2)
            )

        def forward(self, x):
            return self.layers(x)

    def init(hidden: int = 16) -> Net:
        torch.manual_seed(0)
        return Net(hidden)

    model = Model(name="torch_app", init=init, dataset=dataset)

    @dataset.reader
    def reader() -> pd.DataFrame:
        return _frame()

    @model.trainer
    def trainer(net: Net, features: pd.DataFrame, target: pd.DataFrame) -> Net:
        X = torch.from_numpy(features.to_numpy(dtype="float32"))
        y = torch.from_numpy(target.to_numpy().ravel())
        opt = torch.optim.Adam(net.parameters(), lr=5e-2)
        loss_fn = torch.nn.CrossEntropyLoss()
        for _ in range(60):
            opt.zero_grad()
            loss = loss_fn(net(X), y)
            loss.backward()
            opt.step()
        return net

    @model.predictor
    def predictor(net: Net, features: pd.DataFrame) -> List[int]:
        with torch.no_grad():
            logits = net(torch.from_numpy(features.to_numpy(dtype="float32")))
        return [int(i) for i in logits.argmax(dim=-1)]

    @model.evaluator
    def evaluator(net: Net, features: pd.DataFrame, target: pd.DataFrame) -> float:
        preds = np.array(predictor(net, features))
        return float((preds == target.to_numpy().ravel()).mean())

    _roundtrip_and_serve(model, tmp_path, hyperparameters={"hidden": 16})


def test_torch_default_loader_reconstructs_from_hyperparameters(tmp_path):
    """The torch artifact branch stores state_dict + hyperparameters; load must
    rebuild via init(hyperparameters) then load_state_dict (reference
    unionml/model.py:970-980)."""
    torch = pytest.importorskip("torch")

    from unionml_tpu.artifact import load_model_object, save_model_object

    net = torch.nn.Linear(3, 2)
    path = tmp_path / "net.pt"
    save_model_object(net, {"out_features": 2}, str(path))

    rebuilt = load_model_object(
        str(path), type(net), init=lambda hp: torch.nn.Linear(3, hp["out_features"])
    )
    for a, b in zip(net.parameters(), rebuilt.parameters()):
        assert torch.equal(a, b)


def test_keras_app_end_to_end(tmp_path):
    keras = pytest.importorskip("tensorflow.keras")

    dataset = Dataset(name="keras_ds", targets=["y"], test_size=0.25)

    def init(hidden: int = 16) -> keras.Model:
        keras.utils.set_random_seed(0)
        net = keras.Sequential(
            [
                keras.layers.Input((DIM,)),
                keras.layers.Dense(hidden, activation="relu"),
                keras.layers.Dense(2, activation="softmax"),
            ]
        )
        net.compile(optimizer=keras.optimizers.Adam(5e-2), loss="sparse_categorical_crossentropy")
        return net

    model = Model(name="keras_app", init=init, dataset=dataset)

    @dataset.reader
    def reader() -> pd.DataFrame:
        return _frame()

    @model.trainer
    def trainer(net: keras.Model, features: pd.DataFrame, target: pd.DataFrame) -> keras.Model:
        net.fit(features.to_numpy(), target.to_numpy().ravel(), epochs=30, verbose=0)
        return net

    @model.predictor
    def predictor(net: keras.Model, features: pd.DataFrame) -> List[int]:
        probs = net.predict(features.to_numpy(), verbose=0)
        return [int(i) for i in probs.argmax(axis=-1)]

    @model.evaluator
    def evaluator(net: keras.Model, features: pd.DataFrame, target: pd.DataFrame) -> float:
        preds = np.array(predictor(net, features))
        return float((preds == target.to_numpy().ravel()).mean())

    # keras save requires a real .keras-suffixed path
    _roundtrip_and_serve(model, tmp_path, hyperparameters={"hidden": 16}, artifact_name="artifact.keras")
