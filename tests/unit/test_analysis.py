"""tpu-lint unit ring: every rule has a must-flag and a near-miss-must-not-flag
fixture, plus suppression-comment, reporter round-trip, CLI, and env-hardening
regression coverage. The companion repo-wide gate (the tree itself must be
lint-clean, under a time budget) lives in test_syntax.py next to the
``compileall`` gate it extends.
"""

import json
import textwrap
from pathlib import Path

import pytest
from click.testing import CliRunner

from unionml_tpu.analysis import render_json, render_text, run_lint
from unionml_tpu.analysis.engine import main as lint_main

REPO = Path(__file__).resolve().parents[2]


def lint_source(tmp_path, source, **kwargs):
    snippet = tmp_path / "snippet.py"
    snippet.write_text(textwrap.dedent(source))
    return run_lint([snippet], **kwargs)


def rule_ids(result):
    return [finding.rule for finding in result.findings]


# --------------------------------------------------------------------- TPU001


def test_tpu001_flags_host_sync_in_jitted_function(tmp_path):
    result = lint_source(
        tmp_path,
        """
        import jax

        @jax.jit
        def step(x):
            print("debugging", x)
            return float(x) + 1.0
        """,
    )
    assert rule_ids(result) == ["TPU001", "TPU001"]
    assert "print()" in result.findings[0].message
    assert "float()" in result.findings[1].message


def test_tpu001_follows_intra_module_call_graph(tmp_path):
    # the sync hides one call away from the jitted entry point — and the same
    # helper NOT reachable from any jit is left alone
    result = lint_source(
        tmp_path,
        """
        import numpy as np
        import jax

        def helper(y):
            return np.asarray(y)

        @jax.jit
        def entry(y):
            return helper(y)
        """,
    )
    assert rule_ids(result) == ["TPU001"]
    assert "np.asarray" in result.findings[0].message


def test_tpu001_near_miss_unjitted_and_static_shape(tmp_path):
    # host syncs OUTSIDE jit are normal host code; int() on .shape is static
    # under jit and must not flag
    result = lint_source(
        tmp_path,
        """
        import numpy as np
        import jax

        def host_side(y):
            print("fine here")
            return np.asarray(y)

        @jax.jit
        def entry(y):
            width = int(y.shape[0])
            return y * width
        """,
    )
    assert result.findings == []


def test_tpu001_flags_module_level_block_until_ready(tmp_path):
    # both spellings of the fence: the method form x.block_until_ready() was
    # always flagged; the module-level jax.block_until_ready(x) form is the
    # same sync and must flag too
    result = lint_source(
        tmp_path,
        """
        import jax

        @jax.jit
        def step(x):
            jax.block_until_ready(x)
            return x + 1
        """,
    )
    assert rule_ids(result) == ["TPU001"]
    assert "jax.block_until_ready" in result.findings[0].message


def test_tpu001_near_miss_non_jax_block_until_ready(tmp_path):
    # a same-named helper from ANOTHER module is not jax's fence — only the
    # dotted jax.block_until_ready form (and the zero-arg method) sync; and
    # jax.block_until_ready OUTSIDE jit is ordinary host code
    result = lint_source(
        tmp_path,
        """
        import jax
        import myfence

        @jax.jit
        def step(x):
            myfence.block_until_ready(x)  # someone else's API, takes an arg
            return x + 1

        def host_side(x):
            return jax.block_until_ready(x)
        """,
    )
    assert result.findings == []


def test_tpu001_jit_wrapped_method(tmp_path):
    # the engine idiom: self._fn = jax.jit(self._impl) marks the method jitted
    result = lint_source(
        tmp_path,
        """
        import jax

        class Engine:
            def __init__(self):
                self._fn = jax.jit(self._impl)

            def _impl(self, x):
                return x.item()
        """,
    )
    assert rule_ids(result) == ["TPU001"]
    assert ".item()" in result.findings[0].message


# --------------------------------------------------------------------- TPU002


def test_tpu002_flags_use_after_donate(tmp_path):
    result = lint_source(
        tmp_path,
        """
        import jax

        def train(state, batches, step_fn):
            compiled = jax.jit(step_fn, donate_argnums=0)
            for batch in batches:
                out = compiled(state, batch)
            return state
        """,
    )
    # two findings: the loop back edge carries the donation into the next
    # iteration's `compiled(state, batch)` (a donated buffer passed again),
    # and the donation reaches `return state`
    assert rule_ids(result) == ["TPU002", "TPU002"]
    assert all("'state'" in f.message for f in result.findings)


def test_tpu002_path_sensitive_branches(tmp_path):
    # a load on the branch the donation did NOT take is clean; the line-order
    # heuristic this replaced would have flagged it
    result = lint_source(
        tmp_path,
        """
        import jax

        def step_once(state, batch, step_fn, dry_run):
            compiled = jax.jit(step_fn, donate_argnums=0)
            if dry_run:
                compiled(state, batch)
            else:
                print(state)
            return None
        """,
    )
    assert rule_ids(result) == []


def test_tpu002_near_miss_rebound_and_variable_argnums(tmp_path):
    # rebinding from the result is THE donation idiom; a non-literal
    # donate_argnums (the debug_disable_donation gate) is not analyzable and
    # must not be guessed at
    result = lint_source(
        tmp_path,
        """
        import jax

        def train(state, batches, step_fn, debug_disable_donation=False):
            donate = () if debug_disable_donation else (0,)
            compiled = jax.jit(step_fn, donate_argnums=donate)
            for batch in batches:
                state, metrics = compiled(state, batch)
            return state

        def train_literal(state, batches, step_fn):
            compiled = jax.jit(step_fn, donate_argnums=0)
            for batch in batches:
                state, metrics = compiled(state, batch)
            return state
        """,
    )
    assert result.findings == []


def test_tpu002_attribute_jit_and_decorator(tmp_path):
    result = lint_source(
        tmp_path,
        """
        from functools import partial

        import jax

        @partial(jax.jit, donate_argnums=(0,))
        def update(carry, x):
            return carry + x

        class Engine:
            def __init__(self):
                self._admit = jax.jit(self._admit_impl, donate_argnums=(0,))

            def _admit_impl(self, cache, row):
                return cache

            def good(self, cache, row):
                cache = self._admit(cache, row)
                return cache

            def bad(self, cache, row):
                out = self._admit(cache, row)
                return cache.shape

        def module_level(carry, xs):
            for x in xs:
                carry2 = update(carry, x)
            return carry
        """,
    )
    # Engine.bad's `cache.shape`, plus two in module_level: the loop back
    # edge carries the donation into the next iteration's `update(carry, x)`
    # and the donation reaches `return carry`
    assert rule_ids(result) == ["TPU002", "TPU002", "TPU002"]
    lines = sorted(finding.line for finding in result.findings)
    assert len(lines) == 3


# --------------------------------------------------------------------- TPU003


def test_tpu003_flags_unlocked_mutation(tmp_path):
    result = lint_source(
        tmp_path,
        """
        import threading

        class Counter:
            def __init__(self):
                self._lock = threading.Lock()
                self.total = 0
                self.items = []

            def bump(self):
                self.total += 1
                self.items.append(1)

            def snapshot(self):
                with self._lock:
                    return self.total, list(self.items)
        """,
    )
    assert rule_ids(result) == ["TPU003", "TPU003"]


def test_tpu003_near_miss_locked_init_and_locked_suffix(tmp_path):
    # mutations under the lock, in __init__, or in a *_locked helper (the
    # caller-holds-the-lock convention) are all clean; so is a class with no
    # lock at all
    result = lint_source(
        tmp_path,
        """
        import threading

        class Counter:
            def __init__(self):
                self._lock = threading.Condition()
                self.total = 0

            def bump(self):
                with self._lock:
                    self.total += 1

            def _drain_locked(self):
                self.total = 0

            def snapshot(self):
                with self._lock:
                    return self.total

        class NoLock:
            def __init__(self):
                self.total = 0

            def bump(self):
                self.total += 1
        """,
    )
    assert result.findings == []


def test_tpu003_unguarded_attribute_not_flagged(tmp_path):
    # an attribute NEVER touched under the lock (engine-thread-only state like
    # the decode carry) is outside the discipline and must not flag
    result = lint_source(
        tmp_path,
        """
        import threading

        class Engine:
            def __init__(self):
                self._lock = threading.Lock()
                self._carry = None
                self.guarded = 0

            def _decode(self):
                self._carry = (1, 2)

            def stats(self):
                with self._lock:
                    return self.guarded
        """,
    )
    assert result.findings == []


# --------------------------------------------------------------------- TPU004


def test_tpu004_flags_blocking_in_loops_and_async(tmp_path):
    result = lint_source(
        tmp_path,
        """
        import subprocess
        import time

        class Engine:
            def _engine_loop(self):
                while True:
                    time.sleep(0.1)

            async def handle_predict(self, request):
                subprocess.run(["echo", "hi"])
                return request
        """,
    )
    assert rule_ids(result) == ["TPU004", "TPU004"]


def test_tpu004_near_miss_plain_method(tmp_path):
    # a throttle in a plain watcher method (not a handler, not a *_loop, not
    # async) is ordinary host code
    result = lint_source(
        tmp_path,
        """
        import time

        class Watcher:
            def poll(self):
                time.sleep(0.5)

        def wait_for_backend():
            time.sleep(1.0)
        """,
    )
    assert result.findings == []


# --------------------------------------------------------------------- TPU005


def test_tpu005_flags_bare_env_parse(tmp_path):
    result = lint_source(
        tmp_path,
        """
        import os

        REPLICAS = int(os.environ.get("REPLICAS", "0"))

        def heartbeat():
            raw = os.getenv("HEARTBEAT_S")
            return float(raw)
        """,
    )
    assert rule_ids(result) == ["TPU005", "TPU005"]


def test_tpu005_near_miss_guarded_parse(tmp_path):
    # the hardened pattern: try/except ValueError with a fallback — and
    # int() on non-env values is out of scope entirely
    result = lint_source(
        tmp_path,
        """
        import os

        def replicas():
            try:
                return max(int(os.environ.get("REPLICAS", "0")), 0)
            except ValueError:
                return 0

        def plain(value):
            return int(value)
        """,
    )
    assert result.findings == []


# --------------------------------------------------------------------- TPU006


def test_tpu006_flags_wall_clock_duration_subtraction(tmp_path):
    result = lint_source(
        tmp_path,
        """
        import time

        def measure(step):
            t0 = time.time()
            step()
            return time.time() - t0
        """,
    )
    assert rule_ids(result) == ["TPU006"]


def test_tpu006_flags_wall_clock_deadline_comparison(tmp_path):
    result = lint_source(
        tmp_path,
        """
        import time

        def drain(timeout_s):
            deadline = time.time() + timeout_s
            while time.time() < deadline:
                pass
        """,
    )
    assert rule_ids(result) == ["TPU006"]


def test_tpu006_flags_from_import_time_spelling(tmp_path):
    result = lint_source(
        tmp_path,
        """
        from time import time

        def elapsed(t0=None):
            start = time()
            return time() - start
        """,
    )
    assert rule_ids(result) == ["TPU006"]


def test_tpu006_near_miss_monotonic_and_lone_timestamps(tmp_path):
    # monotonic pairing is the FIX; a lone time.time() timestamp (heartbeat
    # files, deployed_at records) is legitimate wall-clock use; and
    # subtracting a wall-clock value from ANOTHER process (file-read
    # heartbeat) is the one case monotonic cannot serve — none may flag
    result = lint_source(
        tmp_path,
        """
        import time

        def measure(step):
            t0 = time.monotonic()
            step()
            return time.monotonic() - t0

        def heartbeat_record():
            return {"deployed_at": time.time()}

        def heartbeat_age(path):
            return max(0.0, time.time() - float(path.read_text().strip()))
        """,
    )
    assert result.findings == []


def test_tpu006_taint_stays_in_scope(tmp_path):
    # a name tainted in one function must not condemn the same name in
    # another scope where it holds a monotonic value
    result = lint_source(
        tmp_path,
        """
        import time

        def wall():
            t0 = time.time()
            return t0

        def mono():
            t0 = time.monotonic()
            return time.monotonic() - t0
        """,
    )
    assert result.findings == []


# --------------------------------------------------------------------- TPU007


def test_tpu007_flags_unlocked_locked_helper_call(tmp_path):
    result = lint_source(
        tmp_path,
        """
        import threading

        class Engine:
            def __init__(self):
                self._lock = threading.Lock()
                self._free_blocks = []

            def _release_blocks_locked(self, ids):
                self._free_blocks.extend(ids)

            def finish(self, ids):
                self._release_blocks_locked(ids)
        """,
    )
    assert rule_ids(result) == ["TPU007"]
    assert "_release_blocks_locked" in result.findings[0].message


def test_tpu007_near_miss_locked_callers_stay_clean(tmp_path):
    # under the lock, from another *_locked method (the contract propagates),
    # from __init__ (unshared construction), on another object (its lock, not
    # ours), and in a lockless class (naming choice, nothing to hold) — none
    # may flag
    result = lint_source(
        tmp_path,
        """
        import threading

        class Engine:
            def __init__(self):
                self._lock = threading.Condition()
                self._free_blocks = []
                self._seed_locked()

            def _seed_locked(self):
                self._free_blocks.append(0)

            def _drain_locked(self):
                self._seed_locked()

            def finish(self):
                with self._lock:
                    self._seed_locked()

            def proxy(self, other):
                other._seed_locked()

        class Lockless:
            def _helper_locked(self):
                pass

            def run(self):
                self._helper_locked()
        """,
    )
    assert result.findings == []


def test_tpu007_nested_with_and_closures(tmp_path):
    # a call under an OUTER with holding the lock is fine even when the inner
    # with manages something else; a closure's body is its own scope and the
    # call inside it is not charged to the enclosing method
    result = lint_source(
        tmp_path,
        """
        import threading

        class Engine:
            def __init__(self):
                self._lock = threading.Lock()

            def _free_locked(self):
                pass

            def drain(self, path):
                with self._lock:
                    with open(path) as fh:
                        self._free_locked()

            def deferred(self):
                def cb():
                    self._free_locked()
                return cb
        """,
    )
    assert result.findings == []


# --------------------------------------------- suppressions, reporters, CLI


def test_suppression_comment_silences_named_rule(tmp_path):
    result = lint_source(
        tmp_path,
        """
        import os

        A = int(os.environ.get("A", "0"))  # tpu-lint: disable=TPU005
        B = int(os.environ.get("B", "0"))  # tpu-lint: disable=TPU001
        C = int(os.environ.get("C", "0"))  # tpu-lint: disable=all
        """,
    )
    # A and C suppressed; B's comment names the wrong rule so the finding stands
    assert rule_ids(result) == ["TPU005"]
    assert result.findings[0].line == 5
    assert [finding.line for finding in result.suppressed] == [4, 6]
    assert result.exit_code() == 1


def test_suppressed_only_tree_is_clean_exit(tmp_path):
    result = lint_source(
        tmp_path,
        """
        import os

        A = int(os.environ.get("A", "0"))  # tpu-lint: disable=TPU005
        """,
    )
    assert result.clean and result.exit_code() == 0
    assert len(result.suppressed) == 1


def test_json_reporter_round_trip(tmp_path):
    result = lint_source(
        tmp_path,
        """
        import os

        A = int(os.environ.get("A", "0"))
        B = int(os.environ.get("B", "0"))  # tpu-lint: disable=TPU005
        """,
    )
    payload = json.loads(render_json(result))
    assert payload["version"] == 1
    assert payload["counts"] == {"TPU005": 1}
    assert payload["exit_code"] == 1
    assert len(payload["findings"]) == 1 and len(payload["suppressed"]) == 1
    finding = payload["findings"][0]
    assert finding["rule"] == "TPU005" and finding["line"] == 4
    assert finding["path"].endswith("snippet.py")
    # text reporter carries the same location and a summary line
    text = render_text(result, show_suppressed=True)
    assert "snippet.py:4" in text and "[suppressed]" in text
    assert "1 finding(s), 1 suppressed" in text


def test_select_and_ignore(tmp_path):
    source = """
        import os
        import time

        A = int(os.environ.get("A", "0"))

        class Engine:
            def _engine_loop(self):
                time.sleep(1)
    """
    only_env = lint_source(tmp_path, source, select=["TPU005"])
    assert rule_ids(only_env) == ["TPU005"]
    no_env = lint_source(tmp_path, source, ignore=["TPU005"])
    assert rule_ids(no_env) == ["TPU004"]
    with pytest.raises(ValueError, match="unknown rule"):
        lint_source(tmp_path, source, select=["TPU999"])


def test_engine_main_exit_codes(tmp_path, capsys):
    bad = tmp_path / "bad.py"
    bad.write_text("import os\nA = int(os.environ['A'])\n")
    clean = tmp_path / "clean.py"
    clean.write_text("x = 1\n")
    assert lint_main([str(clean)]) == 0
    assert lint_main([str(bad)]) == 1
    capsys.readouterr()
    assert lint_main([str(bad), "--format", "json"]) == 1
    payload = json.loads(capsys.readouterr().out)
    assert payload["counts"] == {"TPU005": 1}
    assert lint_main([str(tmp_path / "missing.py")]) == 2
    assert lint_main([str(bad), "--select", "NOPE"]) == 2
    syntax_error = tmp_path / "broken.py"
    syntax_error.write_text("def f(:\n")
    assert lint_main([str(syntax_error)]) == 2


def test_cli_lint_command(tmp_path):
    from unionml_tpu.cli import app

    bad = tmp_path / "bad.py"
    bad.write_text("import os\nA = int(os.environ['A'])\n")
    runner = CliRunner()
    result = runner.invoke(app, ["lint", str(bad)])
    assert result.exit_code == 1
    assert "TPU005" in result.output
    result = runner.invoke(app, ["lint", str(bad), "--format", "json"])
    assert result.exit_code == 1
    assert json.loads(result.output)["counts"] == {"TPU005": 1}
    result = runner.invoke(app, ["lint", str(bad), "--ignore", "TPU005"])
    assert result.exit_code == 0


# ------------------------------------------------- env-hardening regression


def test_serve_dp_replicas_tolerates_garbage(monkeypatch, caplog):
    from unionml_tpu._logging import logger
    from unionml_tpu.defaults import SERVE_DP_REPLICAS_ENV_VAR, serve_dp_replicas

    monkeypatch.setattr(logger, "propagate", True)  # let caplog's root handler see records
    monkeypatch.delenv(SERVE_DP_REPLICAS_ENV_VAR, raising=False)
    assert serve_dp_replicas() == 0
    monkeypatch.setenv(SERVE_DP_REPLICAS_ENV_VAR, "3")
    assert serve_dp_replicas() == 3
    monkeypatch.setenv(SERVE_DP_REPLICAS_ENV_VAR, "-2")
    assert serve_dp_replicas() == 0  # clamped, not crashed
    with caplog.at_level("WARNING", logger="unionml_tpu"):
        monkeypatch.setenv(SERVE_DP_REPLICAS_ENV_VAR, "abc")
        assert serve_dp_replicas() == 0
    assert any("abc" in record.message for record in caplog.records)


def test_env_helpers_warn_and_fall_back(monkeypatch, caplog):
    from unionml_tpu._logging import logger
    from unionml_tpu.defaults import env_float, env_int

    monkeypatch.setattr(logger, "propagate", True)  # let caplog's root handler see records
    monkeypatch.setenv("UNIONML_TPU_TEST_KNOB", "not-a-number")
    with caplog.at_level("WARNING", logger="unionml_tpu"):
        assert env_int("UNIONML_TPU_TEST_KNOB", 7) == 7
        assert env_float("UNIONML_TPU_TEST_KNOB", 2.5) == 2.5
    assert sum("not-a-number" in record.message for record in caplog.records) == 2
    monkeypatch.setenv("UNIONML_TPU_TEST_KNOB", "  42 ")
    assert env_int("UNIONML_TPU_TEST_KNOB", 7) == 42
    monkeypatch.setenv("UNIONML_TPU_TEST_KNOB", "0.05")
    assert env_float("UNIONML_TPU_TEST_KNOB", 5.0, minimum=0.1) == 0.1
    monkeypatch.setenv("UNIONML_TPU_TEST_KNOB", "")
    assert env_int("UNIONML_TPU_TEST_KNOB", 7) == 7


# --------------------------------------------------------------------- TPU008


def test_tpu008_flags_unjoined_attribute_thread(tmp_path):
    result = lint_source(
        tmp_path,
        """
        import threading

        class Engine:
            def start(self):
                self._thread = threading.Thread(target=self._loop, daemon=True)
                self._thread.start()

            def close(self):
                self._running = False
        """,
    )
    assert rule_ids(result) == ["TPU008"]
    assert "self._thread" in result.findings[0].message


def test_tpu008_flags_fire_and_forget_and_unjoined_local(tmp_path):
    result = lint_source(
        tmp_path,
        """
        import threading

        class Fleet:
            def kick(self):
                threading.Thread(target=self._loop).start()

            def spawn(self):
                worker = threading.Thread(target=self._loop)
                worker.start()

            def close(self):
                pass
        """,
    )
    assert rule_ids(result) == ["TPU008", "TPU008"]


def test_tpu008_near_misses_stay_clean(tmp_path):
    # joined attribute (the engine idiom), join-through-local-alias (join
    # outside the lock), local joined in-method, container-tracked workers,
    # local promoted to an attribute, a class without close(), and a
    # module-level function — none may flag
    result = lint_source(
        tmp_path,
        """
        import threading

        class Engine:
            def start(self):
                self._thread = threading.Thread(target=self._loop, daemon=True)
                self._thread.start()

            def close(self):
                thread = self._thread
                if thread is not None:
                    thread.join(timeout=10)

        class Warmup:
            def run(self):
                helper = threading.Thread(target=self._probe)
                helper.start()
                helper.join()

            def close(self):
                pass

        class Pool:
            def grow(self):
                worker = threading.Thread(target=self._loop)
                self._workers.append(worker)
                worker.start()

            def promote(self):
                t = threading.Thread(target=self._loop)
                self._scaler = t
                t.start()

            def close(self):
                for worker in self._workers:
                    worker.join()
                self._scaler.join()

        class NoClose:
            def fire(self):
                threading.Thread(target=self._loop).start()

        def module_level():
            threading.Thread(target=print).start()
        """,
    )
    assert rule_ids(result) == []


def test_tpu008_suppression_comment(tmp_path):
    result = lint_source(
        tmp_path,
        """
        import threading

        class Engine:
            def start(self):
                self._thread = threading.Thread(target=self._loop)  # tpu-lint: disable=TPU008

            def close(self):
                pass
        """,
    )
    assert rule_ids(result) == []
    assert [finding.rule for finding in result.suppressed] == ["TPU008"]


# --------------------------------------------------------------------- TPU009


def test_tpu009_flags_request_keyed_dict_without_eviction(tmp_path):
    result = lint_source(
        tmp_path,
        """
        class Registry:
            def __init__(self):
                self._states = {}

            def admit(self, tenant):
                self._states[tenant] = 1
        """,
    )
    assert rule_ids(result) == ["TPU009"]
    assert "self._states" in result.findings[0].message


def test_tpu009_flags_setdefault_and_attribute_keys(tmp_path):
    result = lint_source(
        tmp_path,
        """
        class Recorder:
            def record(self, trace):
                self._inflight.setdefault(trace.request_id, []).append(trace)

        class Census:
            def note(self, session):
                self._counts[session.tenant] = self._counts.get(session.tenant, 0) + 1
        """,
    )
    assert rule_ids(result) == ["TPU009", "TPU009"]


def test_tpu009_near_misses_stay_clean(tmp_path):
    # pop-based eviction, popitem-bounded LRU, del-based pruning, a len()
    # bound check, the filtered-rebuild idiom, server-chosen keys (slot
    # indices), and module-level dicts — none may flag
    result = lint_source(
        tmp_path,
        """
        class PerRequest:
            def start(self, request_id):
                self._inflight[request_id] = 1

            def finish(self, request_id):
                self._inflight.pop(request_id, None)

        class BoundedLRU:
            def note(self, key):
                self._affinity[key] = 1
                while len(self._affinity) > self._capacity:
                    self._affinity.popitem(last=False)

        class Pruned:
            def select(self, tenant):
                self._deficit[tenant] = 0.0
                for tenant in list(self._deficit):
                    del self._deficit[tenant]

        class Rebuilt:
            def note(self, key):
                self._affinity[key] = 1

            def resize(self, n):
                self._affinity = {k: v for k, v in self._affinity.items() if v < n}

        class SlotKeyed:
            def admit(self, slot, session):
                self._sessions[slot] = session

        _MODULE_LEVEL = {}

        def module_insert(tenant):
            _MODULE_LEVEL[tenant] = 1
        """,
    )
    assert rule_ids(result) == []


def test_tpu009_suppression_comment(tmp_path):
    result = lint_source(
        tmp_path,
        """
        class Registry:
            def admit(self, tenant):
                self._states[tenant] = 1  # tpu-lint: disable=TPU009
        """,
    )
    assert rule_ids(result) == []
    assert [finding.rule for finding in result.suppressed] == ["TPU009"]


# ----------------------------------------------- whole-program project rules


def lint_pkg(tmp_path, files, **kwargs):
    """Write a multi-module package fixture and lint the whole tree — the
    cross-module rules only exist at this granularity."""
    pkg = tmp_path / "pkg"
    pkg.mkdir(exist_ok=True)
    (pkg / "__init__.py").write_text("")
    for name, source in files.items():
        (pkg / name).write_text(textwrap.dedent(source))
    return run_lint([pkg], **kwargs)


def test_tpu010_flags_cross_module_lock_cycle(tmp_path):
    # thread 1: Fleet._scale_lock -> Engine._lock; thread 2: Engine._lock ->
    # Fleet._scale_lock (through an annotated callback parameter) — the cycle
    # spans two modules and is invisible to any per-file rule
    result = lint_pkg(
        tmp_path,
        {
            "fleet.py": """
            import threading

            from pkg.engine import Engine


            class Fleet:
                def __init__(self):
                    self._scale_lock = threading.Lock()
                    self._engine = Engine()

                def scale(self):
                    with self._scale_lock:
                        self._engine.drain(self)
            """,
            "engine.py": """
            import threading

            import pkg.fleet


            class Engine:
                def __init__(self):
                    self._lock = threading.Lock()

                def drain(self, fleet: pkg.fleet.Fleet):
                    with self._lock:
                        fleet.scale()
            """,
        },
    )
    assert rule_ids(result) == ["TPU010"]
    message = result.findings[0].message
    assert "lock-order cycle" in message
    assert "[path 1]" in message and "[path 2]" in message
    assert "Fleet._scale_lock" in message and "Engine._lock" in message


def test_tpu010_near_miss_consistent_order_and_reentry(tmp_path):
    # one global order (_scale_lock always before _lock) is the FIX and must
    # not flag; re-entering the same lock through a helper is out of scope
    result = lint_pkg(
        tmp_path,
        {
            "fleet.py": """
            import threading

            from pkg.engine import Engine


            class Fleet:
                def __init__(self):
                    self._scale_lock = threading.Lock()
                    self._engine = Engine()

                def scale(self):
                    with self._scale_lock:
                        self._engine.drain()

                def fast_scale(self):
                    with self._scale_lock:
                        self._engine.drain()
            """,
            "engine.py": """
            import threading


            class Engine:
                def __init__(self):
                    self._lock = threading.Condition()

                def drain(self):
                    with self._lock:
                        self._free_locked()

                def _free_locked(self):
                    pass
            """,
        },
    )
    assert rule_ids(result) == []


def test_tpu010_locked_convention_participates(tmp_path):
    # a *_locked method runs with its class's lock held by contract: calling
    # another class's locking method from it is an edge; the reverse direction
    # in the other module closes the cycle
    result = lint_pkg(
        tmp_path,
        {
            "cache.py": """
            import threading

            from pkg.pool import Pool


            class Cache:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._pool = Pool()

                def _evict_locked(self):
                    self._pool.grab()
            """,
            "pool.py": """
            import threading

            import pkg.cache


            class Pool:
                def __init__(self):
                    self._lock = threading.Lock()

                def grab(self):
                    with self._lock:
                        pass

                def rebalance(self, cache: pkg.cache.Cache):
                    with self._lock:
                        cache._evict_locked()
            """,
        },
    )
    assert rule_ids(result) == ["TPU010"]


def test_tpu010_textually_nested_with_statements(tmp_path):
    # `with self._a:` with `with self._b:` as a SEPARATE nested statement (not
    # the `with a, b:` single-statement form) — the inner acquisition must be
    # recorded with the outer lock held, so opposite nesting in two methods is
    # a cycle
    result = lint_pkg(
        tmp_path,
        {
            "pair.py": """
            import threading


            class Pair:
                def __init__(self):
                    self._a = threading.Lock()
                    self._b = threading.Lock()

                def forward(self):
                    with self._a:
                        with self._b:
                            pass

                def backward(self):
                    with self._b:
                        with self._a:
                            pass
            """,
        },
    )
    assert rule_ids(result) == ["TPU010"]
    message = result.findings[0].message
    assert "Pair._a" in message and "Pair._b" in message


def test_tpu010_call_under_nested_with_carries_inner_lock(tmp_path):
    # a call under the INNER of two textually nested withs must carry both
    # locks in its held-set: the b -> c edge exists only because the
    # grab_c() call site holds _b, and backward's c -> b closes the cycle
    result = lint_pkg(
        tmp_path,
        {
            "trio.py": """
            import threading


            class Trio:
                def __init__(self):
                    self._a = threading.Lock()
                    self._b = threading.Lock()
                    self._c = threading.Lock()

                def forward(self):
                    with self._a:
                        with self._b:
                            self.grab_c()

                def grab_c(self):
                    with self._c:
                        pass

                def backward(self):
                    with self._c, self._b:
                        pass
            """,
        },
    )
    assert rule_ids(result) == ["TPU010"]
    assert "Trio._b" in result.findings[0].message and "Trio._c" in result.findings[0].message


def test_tpu011_flags_varying_static_args_cross_module(tmp_path):
    result = lint_pkg(
        tmp_path,
        {
            "kernels.py": """
            import functools

            import jax


            @functools.partial(jax.jit, static_argnames=("steps",))
            def decode(params, carry, steps):
                return carry


            @functools.partial(jax.jit, static_argnums=(1,))
            def gather(rows, width):
                return rows
            """,
            "serve.py": """
            from pkg.kernels import decode, gather


            def storm(params, carry, prompt):
                out = carry
                for n in range(10):
                    out = decode(params, out, steps=n)
                return gather(out, len(prompt))
            """,
        },
    )
    assert rule_ids(result) == ["TPU011", "TPU011"]
    assert "loop variable 'n'" in result.findings[0].message
    assert "len() of parameter 'prompt'" in result.findings[1].message
    assert "recompile" in result.findings[0].message or "trace+compile" in result.findings[0].message


def test_tpu011_near_miss_constants_and_forwarded_params(tmp_path):
    # module constants, config attributes, and plain forwarded parameters are
    # not provably varying — the classic bucketed-steps call must stay clean
    result = lint_pkg(
        tmp_path,
        {
            "kernels.py": """
            import functools

            import jax


            @functools.partial(jax.jit, static_argnames=("steps",))
            def decode(params, carry, steps):
                return carry
            """,
            "serve.py": """
            from pkg.kernels import decode

            CHUNK = 64


            def ok(params, carry, steps):
                out = decode(params, carry, steps=CHUNK)
                out = decode(params, out, steps=steps)
                return out
            """,
        },
    )
    assert rule_ids(result) == []


def test_tpu011_attribute_binding_static_argnums(tmp_path):
    # the engine idiom: self._fn = jax.jit(impl, static_argnums=...) — the
    # hazard is at the method's call site, possibly far from the wrap
    result = lint_pkg(
        tmp_path,
        {
            "engine.py": """
            import jax


            def gather_rows(rows, table, width):
                return rows


            class Engine:
                def __init__(self):
                    self._gather = jax.jit(gather_rows, static_argnums=(2,))

                def admit(self, rows, table, lengths):
                    for length in lengths:
                        rows = self._gather(rows, table, length)
                    return rows
            """,
        },
    )
    assert rule_ids(result) == ["TPU011"]
    assert "loop variable 'length'" in result.findings[0].message


def test_tpu011_nested_for_loops_accumulate_targets(tmp_path):
    # a for directly inside another for (no intervening statement) must still
    # register its own target: the inner loop variable in a static position is
    # the canonical recompile-storm shape
    result = lint_pkg(
        tmp_path,
        {
            "kernels.py": """
            import functools

            import jax


            @functools.partial(jax.jit, static_argnames=("steps",))
            def decode(params, carry, steps):
                return carry
            """,
            "serve.py": """
            from pkg.kernels import decode


            def storm(params, carry, batches):
                out = carry
                for batch in batches:
                    for n in range(4):
                        out = decode(params, out, steps=n)
                return out
            """,
        },
    )
    assert rule_ids(result) == ["TPU011"]
    assert "loop variable 'n'" in result.findings[0].message


def test_tpu011_jit_decorated_method_static_argnums(tmp_path):
    # decorator static_argnums count the unbound `self` (position 2 = width),
    # but the self.gather(...) call site has no receiver argument — the check
    # must look at call position 1, not 2
    result = lint_pkg(
        tmp_path,
        {
            "engine.py": """
            import functools

            import jax


            class Engine:
                @functools.partial(jax.jit, static_argnums=(2,))
                def gather(self, rows, width):
                    return rows

                def admit(self, rows, lengths):
                    for length in lengths:
                        rows = self.gather(rows, length)
                    return rows
            """,
        },
    )
    assert rule_ids(result) == ["TPU011"]
    assert "loop variable 'length'" in result.findings[0].message


def test_tpu012_flags_executor_and_thread_holes_cross_module(tmp_path):
    result = lint_pkg(
        tmp_path,
        {
            "tenancy.py": """
            import contextvars

            _tenant_var = contextvars.ContextVar("tenant", default=None)


            def current_tenant():
                return _tenant_var.get()
            """,
            "handler.py": """
            import threading

            from pkg.tenancy import current_tenant


            def bill_stream():
                return current_tenant()


            async def pull(loop):
                return await loop.run_in_executor(None, bill_stream)


            def spawn():
                threading.Thread(target=bill_stream).start()
            """,
        },
    )
    assert rule_ids(result) == ["TPU012", "TPU012"]
    assert "bill_stream" in result.findings[0].message
    assert "_tenant_var" in result.findings[0].message
    assert "ctx.run" in result.findings[0].message
    assert "Thread target" in result.findings[1].message


def test_tpu012_near_miss_wrapped_and_no_read(tmp_path):
    # the PR 5 fix idiom (ctx.run), a partial(ctx.run, fn) wrap, a target that
    # reads no contextvar, and an unresolvable stored callable — none may flag
    result = lint_pkg(
        tmp_path,
        {
            "tenancy.py": """
            import contextvars

            _tenant_var = contextvars.ContextVar("tenant", default=None)


            def current_tenant():
                return _tenant_var.get()
            """,
            "handler.py": """
            import contextvars
            import functools
            import threading

            from pkg.tenancy import current_tenant


            def bill_stream():
                return current_tenant()


            def plain():
                return 1


            async def wrapped(loop):
                ctx = contextvars.copy_context()
                return await loop.run_in_executor(None, ctx.run, bill_stream)


            def wrapped_thread():
                ctx = contextvars.copy_context()
                threading.Thread(target=functools.partial(ctx.run, bill_stream)).start()


            async def no_read(loop):
                return await loop.run_in_executor(None, plain)


            class Batcher:
                def __init__(self, fn):
                    self._fn = fn

                async def call(self, loop):
                    return await loop.run_in_executor(None, self._fn)
            """,
        },
    )
    assert rule_ids(result) == []


def test_tpu001_cross_module_reachability(tmp_path):
    # the host sync hides in a helper module the jitted entry imports — the
    # per-file pass cannot see it; the index-backed pass must
    result = lint_pkg(
        tmp_path,
        {
            "helpers.py": """
            import numpy as np


            def to_host(y):
                return np.asarray(y)
            """,
            "main.py": """
            import jax

            from pkg.helpers import to_host


            @jax.jit
            def entry(y):
                return to_host(y)
            """,
        },
    )
    assert rule_ids(result) == ["TPU001"]
    assert result.findings[0].path.endswith("helpers.py")
    assert "np.asarray" in result.findings[0].message


def test_tpu001_cross_module_near_miss_unreachable_helper(tmp_path):
    # same helper, never called from a jit entry: ordinary host code
    result = lint_pkg(
        tmp_path,
        {
            "helpers.py": """
            import numpy as np


            def to_host(y):
                return np.asarray(y)
            """,
            "main.py": """
            import jax

            from pkg.helpers import to_host


            @jax.jit
            def entry(y):
                return y + 1


            def host_side(y):
                return to_host(y)
            """,
        },
    )
    assert rule_ids(result) == []


def test_tpu002_cross_module_donor(tmp_path):
    # the donor is decorated in kernels.py; train.py imports and misuses it —
    # reading `state` after its buffer was donated, two modules away
    result = lint_pkg(
        tmp_path,
        {
            "kernels.py": """
            from functools import partial

            import jax


            @partial(jax.jit, donate_argnums=(0,))
            def update(carry, x):
                return carry + x
            """,
            "train.py": """
            from pkg.kernels import update


            def train(state, xs):
                for x in xs:
                    out = update(state, x)
                return state


            def train_ok(state, xs):
                for x in xs:
                    state = update(state, x)
                return state
            """,
        },
    )
    # two findings in train(): the loop back edge carries the donation into
    # the next iteration's `update(state, x)`, and it reaches `return state`
    assert rule_ids(result) == ["TPU002", "TPU002"]
    assert all(f.path.endswith("train.py") for f in result.findings)
    assert all("'state'" in f.message for f in result.findings)


def test_project_rule_findings_respect_suppressions(tmp_path):
    result = lint_pkg(
        tmp_path,
        {
            "helpers.py": """
            import numpy as np


            def to_host(y):
                return np.asarray(y)  # tpu-lint: disable=TPU001
            """,
            "main.py": """
            import jax

            from pkg.helpers import to_host


            @jax.jit
            def entry(y):
                return to_host(y)
            """,
        },
    )
    assert rule_ids(result) == []
    assert [finding.rule for finding in result.suppressed] == ["TPU001"]


# --------------------------------------------------------------------- TPU013


def test_tpu013_flags_collective_under_lock(tmp_path):
    # the three spellings: a with-block collective, a *_locked method body
    # (caller holds the lock), and a control-plane RPC on a host handle
    result = lint_source(
        tmp_path,
        """
        import threading

        from jax.experimental import multihost_utils


        class Coordinator:
            def __init__(self):
                self._lock = threading.Lock()
                self.hosts = []

            def rebalance(self):
                with self._lock:
                    multihost_utils.sync_global_devices("rebalance")

            def _sync_locked(self):
                broadcast_one_to_all(None)

            def route(self, i):
                with self._lock:
                    self.hosts[i].probe([1, 2])
        """,
    )
    assert rule_ids(result) == ["TPU013", "TPU013", "TPU013"]
    assert "multihost_utils.sync_global_devices" in result.findings[0].message
    assert "self._lock" in result.findings[0].message
    assert "broadcast_one_to_all" in result.findings[1].message
    assert "probe" in result.findings[2].message


def test_tpu013_flags_jax_distributed_and_repo_helpers(tmp_path):
    result = lint_source(
        tmp_path,
        """
        import threading

        import jax
        from unionml_tpu import distributed


        class Fleet:
            def __init__(self):
                self._state_lock = threading.Condition()

            def join(self):
                with self._state_lock:
                    jax.distributed.initialize()

            def agree_config(self, cfg):
                with self._state_lock:
                    return distributed.agree(cfg)
        """,
    )
    assert rule_ids(result) == ["TPU013", "TPU013"]
    assert "jax.distributed.initialize" in result.findings[0].message
    assert "distributed.agree" in result.findings[1].message


def test_tpu013_near_miss_outside_lock_and_lockless_class(tmp_path):
    # the fix idiom (snapshot under the lock, rendezvous outside), collectives
    # in a class with no lock, ordinary calls under the lock, and __init__ are
    # all clean
    result = lint_source(
        tmp_path,
        """
        import threading

        from jax.experimental import multihost_utils


        class Coordinator:
            def __init__(self):
                self._lock = threading.Lock()
                multihost_utils.sync_global_devices("construction")  # pre-sharing

            def rebalance(self):
                with self._lock:
                    plan = self._plan()
                multihost_utils.sync_global_devices("rebalance")
                return plan

            def _plan(self):
                with self._lock:
                    return len("plan")


        class LockFree:
            def sync(self):
                multihost_utils.sync_global_devices("fine")
        """,
    )
    assert result.findings == []


# ------------------------------------------------- index cache + incremental


def test_index_cache_invalidation_on_edit(tmp_path):
    snippet = tmp_path / "snippet.py"
    snippet.write_text("x = 1\n")
    first = run_lint([snippet])
    assert first.clean and first.index_stats == {"hits": 0, "misses": 1}
    warm = run_lint([snippet])
    assert warm.index_stats == {"hits": 1, "misses": 0}
    # the edit introduces a violation: the stale cached summary/findings must
    # be dropped on the content-hash mismatch
    snippet.write_text("import os\nA = int(os.environ['A'])\n")
    edited = run_lint([snippet])
    assert edited.index_stats == {"hits": 0, "misses": 1}
    assert rule_ids(edited) == ["TPU005"]
    # and a fix is picked up the same way
    snippet.write_text("x = 2\n")
    assert run_lint([snippet]).clean


def test_run_lint_only_reports_named_files_with_whole_program_index(tmp_path):
    pkg = tmp_path / "pkg"
    pkg.mkdir()
    (pkg / "__init__.py").write_text("")
    (pkg / "helpers.py").write_text(
        textwrap.dedent(
            """
            import numpy as np


            def to_host(y):
                return np.asarray(y)
            """
        )
    )
    (pkg / "main.py").write_text(
        textwrap.dedent(
            """
            import os

            import jax

            from pkg.helpers import to_host

            A = int(os.environ["A"])


            @jax.jit
            def entry(y):
                return to_host(y)
            """
        )
    )
    # only= restricts REPORTING, not the index: helpers.py's TPU001 finding
    # (which needs main.py's jit entry to exist) is filtered out, main.py's
    # TPU005 stays
    result = run_lint([pkg], only=[pkg / "main.py"])
    assert rule_ids(result) == ["TPU005"]
    assert result.files == 1
    full = run_lint([pkg])
    assert sorted(rule_ids(full)) == ["TPU001", "TPU005"]


def test_changed_only_cli_against_git(tmp_path, monkeypatch, capsys):
    import subprocess

    repo = tmp_path / "repo"
    repo.mkdir()
    git = lambda *args: subprocess.run(
        ["git", "-c", "user.email=t@t", "-c", "user.name=t", *args],
        cwd=repo,
        check=True,
        capture_output=True,
    )
    git("init", "-q")
    (repo / "stable.py").write_text("import os\nB = int(os.environ['B'])\n")
    (repo / "touched.py").write_text("x = 1\n")
    git("add", ".")
    git("commit", "-q", "-m", "seed")
    (repo / "touched.py").write_text("import os\nA = int(os.environ['A'])\n")
    monkeypatch.chdir(repo)
    # full run sees both findings; --changed-only reports just the edited file
    assert lint_main([str(repo)]) == 1
    capsys.readouterr()
    assert lint_main([str(repo), "--changed-only", "HEAD", "--format", "json"]) == 1
    payload = json.loads(capsys.readouterr().out)
    assert payload["counts"] == {"TPU005": 1}
    assert payload["findings"][0]["path"].endswith("touched.py")
    assert payload["files"] == 1


# ----------------------------------------------------------- SARIF reporter


def test_sarif_reporter_round_trip(tmp_path):
    from unionml_tpu.analysis import render_sarif

    result = lint_source(
        tmp_path,
        """
        import os

        A = int(os.environ.get("A", "0"))
        B = int(os.environ.get("B", "0"))  # tpu-lint: disable=TPU005
        """,
    )
    payload = json.loads(render_sarif(result))
    assert payload["version"] == "2.1.0"
    assert "sarif-schema-2.1.0" in payload["$schema"]
    run = payload["runs"][0]
    assert run["tool"]["driver"]["name"] == "tpu-lint"
    rule_index = {rule["id"] for rule in run["tool"]["driver"]["rules"]}
    assert {"TPU001", "TPU005", "TPU010", "TPU011", "TPU012", "TPU013"} <= rule_index
    active = [r for r in run["results"] if "suppressions" not in r]
    suppressed = [r for r in run["results"] if "suppressions" in r]
    assert len(active) == 1 and len(suppressed) == 1
    assert active[0]["ruleId"] == "TPU005"
    region = active[0]["locations"][0]["physicalLocation"]["region"]
    assert region["startLine"] == 4 and region["startColumn"] >= 1
    assert suppressed[0]["suppressions"] == [
        {"kind": "inSource", "justification": "# tpu-lint: disable"}
    ]
    assert run["invocations"][0]["executionSuccessful"] is True


def test_sarif_cli_format(tmp_path, capsys):
    bad = tmp_path / "bad.py"
    bad.write_text("import os\nA = int(os.environ['A'])\n")
    assert lint_main([str(bad), "--format", "sarif"]) == 1
    payload = json.loads(capsys.readouterr().out)
    assert payload["version"] == "2.1.0"
    assert payload["runs"][0]["results"][0]["ruleId"] == "TPU005"
    # JSON schema version is untouched by the SARIF addition
    assert lint_main([str(bad), "--format", "json"]) == 1
    assert json.loads(capsys.readouterr().out)["version"] == 1


# --------------------------------------------------------------------- TPU014


def _lint_bench_source(tmp_path, source):
    """TPU014 is path-scoped to benchmarks/ and workloads/: write the snippet
    under a benchmarks dir so the rule engages."""
    bench_dir = tmp_path / "benchmarks"
    bench_dir.mkdir(exist_ok=True)
    snippet = bench_dir / "bench_snippet.py"
    snippet.write_text(textwrap.dedent(source))
    return run_lint([snippet])


def test_tpu014_flags_global_rng_draws_in_benchmarks(tmp_path):
    result = _lint_bench_source(
        tmp_path,
        """
        import random

        import numpy as np


        def arrivals(n):
            offsets = [random.expovariate(2.0) for _ in range(n)]
            prompts = np.random.randint(1, 90, size=8)
            random.shuffle(offsets)
            return offsets, prompts
        """,
    )
    assert rule_ids(result) == ["TPU014", "TPU014", "TPU014"]
    assert "random.expovariate" in result.findings[0].message
    assert "np.random.randint" in result.findings[1].message
    assert "random.Random(seed)" in result.findings[0].message  # the fix idiom


def test_tpu014_seeded_generators_and_jax_keys_stay_clean(tmp_path):
    # the fixed forms: Random(seed) instances, default_rng(seed) Generators,
    # jax.random keys — and rng METHOD calls are never confused with module
    # draws
    result = _lint_bench_source(
        tmp_path,
        """
        import random

        import jax
        import numpy as np


        def arrivals(n, seed):
            rng = random.Random(seed)
            gen = np.random.default_rng(seed)
            key = jax.random.PRNGKey(seed)
            offsets = [rng.expovariate(2.0) for _ in range(n)]
            prompts = gen.integers(1, 90, size=8)
            noise = jax.random.normal(key, (4,))
            return offsets, prompts, noise
        """,
    )
    assert rule_ids(result) == []


def test_tpu014_out_of_scope_paths_stay_clean(tmp_path):
    # the same global draw OUTSIDE benchmarks/workloads is out of scope:
    # library code that wants entropy (id minting) is not the rule's business
    result = lint_source(
        tmp_path,
        """
        import random


        def jitter():
            return random.random()
        """,
    )
    assert rule_ids(result) == []


def test_tpu014_workloads_scope_and_global_seed(tmp_path):
    # unionml_tpu/workloads is in scope too, and global random.seed() — the
    # "seeded but shared" trap — is flagged alongside the draws
    wl = tmp_path / "workloads"
    wl.mkdir()
    snippet = wl / "scenario.py"
    snippet.write_text(textwrap.dedent(
        """
        import random


        def build(seed):
            random.seed(seed)
            return [random.randrange(90) for _ in range(4)]
        """
    ))
    result = run_lint([snippet])
    assert rule_ids(result) == ["TPU014", "TPU014"]
    assert "random.seed" in result.findings[0].message


# --------------------------------------------------------------------- TPU015


def test_tpu015_flags_unbounded_retry_loops(tmp_path):
    result = lint_source(
        tmp_path,
        """
        import itertools
        from urllib.request import urlopen


        def hammer(host):
            while True:
                try:
                    host.ping()
                    break
                except OSError:
                    continue


        def hammer_http(url):
            for _ in itertools.count():
                urlopen(url)
        """,
    )
    assert rule_ids(result) == ["TPU015", "TPU015"]
    assert "host.ping" in result.findings[0].message
    assert "_call_retry" in result.findings[0].message  # the fix idiom
    assert "urlopen" in result.findings[1].message


def test_tpu015_bounded_and_paced_loops_stay_clean(tmp_path):
    # the three brakes: a bounded for-range envelope (the
    # RemoteHost._call_retry shape), a Compare-bounded while (attempt counter
    # or deadline), and an Event.wait-paced watcher loop — plus the walk of a
    # finite host list, which is one attempt per host, not a retry
    result = lint_source(
        tmp_path,
        """
        import time


        def walk(hosts, prompt):
            for host in hosts:
                host.probe(prompt)


        def bounded_envelope(host):
            for attempt in range(3):
                try:
                    return host.ping()
                except OSError:
                    time.sleep(0.05 * (attempt + 1))


        def deadline_bounded(host, deadline, clock):
            while clock() < deadline:
                try:
                    return host.ping()
                except OSError:
                    time.sleep(0.1)


        class Reconciler:
            def loop(self):
                while not self._stop.wait(0.2):
                    self.hosts[0].ping()
        """,
    )
    assert rule_ids(result) == []


def test_tpu015_sleepless_while_true_without_network_stays_clean(tmp_path):
    # unbounded loops that never touch the network are some other rule's
    # business (a decode engine's dispatch loop, a queue drain)
    result = lint_source(
        tmp_path,
        """
        def drain(queue):
            while True:
                item = queue.get()
                if item is None:
                    return
        """,
    )
    assert rule_ids(result) == []


def test_tpu015_nested_def_does_not_leak_pacing_or_calls(tmp_path):
    # a sleep INSIDE a nested function does not pace the outer loop, and a
    # network call inside a nested function is not the loop's call
    result = lint_source(
        tmp_path,
        """
        import time


        def bad(host):
            while True:
                def later():
                    time.sleep(1.0)
                host.ping()


        def clean(host):
            while True:
                def work():
                    host.ping()
                register(work)
                if done():
                    return
        """,
    )
    assert rule_ids(result) == ["TPU015"]


# ------------------------------------------------------- CFG construction


def _cfg_of(source, name="f"):
    import ast

    from unionml_tpu.analysis.cfg import build_cfg

    tree = ast.parse(textwrap.dedent(source))
    func = next(
        n
        for n in ast.walk(tree)
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)) and n.name == name
    )
    return build_cfg(func)


def _nodes_calling(cfg, fname):
    import ast

    out = []
    for node in cfg.statement_nodes():
        for expr in node.exprs:
            if expr is None:
                continue
            for sub in ast.walk(expr):
                if (
                    isinstance(sub, ast.Call)
                    and isinstance(sub.func, ast.Name)
                    and sub.func.id == fname
                ):
                    out.append(node)
    return out


def test_cfg_try_finally_with_return_threads_the_finally():
    # the finally body runs on the return path: the release node's successors
    # reach function EXIT, and no path skips it
    cfg = _cfg_of(
        """
        def f(x, release):
            try:
                return x
            finally:
                release()
        """
    )
    releases = _nodes_calling(cfg, "release")
    assert releases, "finally body missing from CFG"
    assert any(
        dst == cfg.exit for node in releases for dst, _ in node.succs
    ), "return continuation does not pass through the finally"


def test_cfg_try_finally_with_break_exits_the_loop():
    # break inside try/finally: the finally copy on the break continuation
    # leads OUT of the loop (to `done()`), not back to the header
    cfg = _cfg_of(
        """
        def f(items, release, done):
            for item in items:
                try:
                    break
                finally:
                    release()
            done()
        """
    )
    done_nids = {n.nid for n in _nodes_calling(cfg, "done")}
    assert done_nids
    assert any(
        dst in done_nids for node in _nodes_calling(cfg, "release") for dst, _ in node.succs
    ), "break continuation does not leave the loop after the finally"


def test_cfg_nested_handlers_with_reraise_route_to_outer_catch_all():
    # the inner handler's bare `raise` lands in the OUTER handler; with the
    # outer being a catch-all and nothing else raising, the function cannot
    # terminate by exception
    cfg = _cfg_of(
        """
        def f(work):
            try:
                try:
                    work()
                except ValueError:
                    raise
            except Exception:
                x = 1
        """
    )
    assert cfg.nodes[cfg.raise_node].preds == []


def test_cfg_with_tuple_target_and_split_exits():
    import ast

    # `with make() as (a, b):` — both names are bound at the with header, and
    # the splitting-style __exit__ gives the normal and exception
    # continuations their own with_exit nodes
    cfg = _cfg_of(
        """
        def f(make, use):
            with make() as (a, b):
                use(a, b)
        """
    )
    header = next(n for n in cfg.statement_nodes() if isinstance(n.stmt, ast.With))
    bound = {
        sub.id
        for expr in header.exprs
        if expr is not None
        for sub in ast.walk(expr)
        if isinstance(sub, ast.Name) and isinstance(sub.ctx, ast.Store)
    }
    assert bound == {"a", "b"}
    exits = [n for n in cfg.statement_nodes() if n.kind == "with_exit"]
    assert len(exits) == 2  # one for normal completion, one for the exc path
    kinds = {kind for n in exits for _, kind in n.succs}
    assert "exc" in kinds  # the exception continuation keeps raising


def test_cfg_while_else_runs_on_normal_exit():
    cfg = _cfg_of(
        """
        def f(n, finish, after):
            while n > 0:
                n -= 1
            else:
                finish()
            after()
        """
    )
    assert cfg.back_edges, "loop has no back edge"
    finish = _nodes_calling(cfg, "finish")
    assert finish, "while/else body missing"
    # else runs off the loop's FALSE edge, then falls through to after()
    assert any(kind == "false" for _, kind in finish[0].preds)
    after_nids = {n.nid for n in _nodes_calling(cfg, "after")}
    assert any(dst in after_nids for dst, _ in finish[0].succs)


def test_cfg_yield_inside_with_is_a_marked_suspension():
    cfg = _cfg_of(
        """
        def f(lock):
            with lock:
                yield 1
        """
    )
    yields = [n for n in cfg.statement_nodes() if n.is_yield]
    assert len(yields) == 1
    # the suspension sits between the with header and its exit
    import ast

    header = next(n for n in cfg.statement_nodes() if isinstance(n.stmt, ast.With))
    assert any(src == header.nid for src, _ in yields[0].preds)


# ------------------------------------------------------- dataflow + dominators


def test_dataflow_exception_edge_drops_the_statements_own_gen():
    # acquire-style fact: generated when the statement COMPLETES, so the exc
    # edge out of the generating statement must not carry it
    import ast

    from unionml_tpu.analysis.dataflow import Problem, solve_forward

    cfg = _cfg_of(
        """
        def f(acquire, use):
            h = acquire()
            use(h)
        """
    )

    class Acquired(Problem):
        def gen_kill(self, node):
            gen = set()
            if node.stmt is not None and isinstance(node.stmt, ast.Assign):
                gen.add("h")
            return gen, set()

    sol = solve_forward(cfg, Acquired())
    use_node = _nodes_calling(cfg, "use")[0]
    assert "h" in sol.in_facts(use_node.nid)  # normal path has the fact
    # but the exception exit only sees facts from use(h)'s OWN exc edge —
    # the assign's exc edge (acquire() itself raised) carries nothing
    assert sol.at_raise == frozenset({"h"})


def test_dominators_branch_join():
    import ast

    from unionml_tpu.analysis.dataflow import dominators

    cfg = _cfg_of(
        """
        def f(cond, a, b, join):
            if cond:
                a()
            else:
                b()
            join()
        """
    )
    dom = dominators(cfg)
    header = next(n for n in cfg.statement_nodes() if isinstance(n.stmt, ast.If))
    a_node = _nodes_calling(cfg, "a")[0]
    join_node = _nodes_calling(cfg, "join")[0]
    assert header.nid in dom[join_node.nid]  # the test runs on every path
    assert a_node.nid not in dom[join_node.nid]  # one branch does not
    assert join_node.nid in dom[join_node.nid]  # reflexive


# --------------------------------------------------------------------- TPU016


def test_tpu016_flags_connection_leaked_on_exception_path(tmp_path):
    # request()/getresponse() can raise after the connection exists — without
    # a try/except-close the socket leaks on every error
    result = lint_source(
        tmp_path,
        """
        from http.client import HTTPConnection

        def fetch(host, payload):
            conn = HTTPConnection(host)
            conn.request("POST", "/step", payload)
            resp = conn.getresponse()
            body = resp.read()
            conn.close()
            return body
        """,
    )
    assert "TPU016" in rule_ids(result)
    assert "conn" in result.findings[0].message


def test_tpu016_near_miss_guarded_and_with_managed(tmp_path):
    # the two clean shapes: close in an except-reraise guard, and the context
    # manager (guaranteed release through with_exit on every continuation)
    result = lint_source(
        tmp_path,
        """
        from http.client import HTTPConnection

        def fetch(host, payload):
            conn = HTTPConnection(host)
            try:
                conn.request("POST", "/step", payload)
                body = conn.getresponse().read()
            except BaseException:
                conn.close()
                raise
            conn.close()
            return body

        def read_config(path):
            with open(path) as handle:
                return handle.read()
        """,
    )
    assert rule_ids(result) == []


# --------------------------------------------------------------------- TPU017


def test_tpu017_flags_charge_without_refund_on_exception(tmp_path):
    result = lint_source(
        tmp_path,
        """
        def submit(registry, tenant, grammar, compile_grammar):
            retry_after = registry.try_admit(tenant)
            if retry_after is not None:
                raise RuntimeError("throttled")
            compile_grammar(grammar)
            return True
        """,
    )
    assert rule_ids(result) == ["TPU017"]
    assert "refund" in result.findings[0].message


def test_tpu017_near_miss_refund_in_except_and_shed_path(tmp_path):
    # the canonical shapes stay clean: refund-and-reraise, and the shed path
    # (non-None retry_after means the bucket was NOT debited)
    result = lint_source(
        tmp_path,
        """
        def submit(registry, tenant, grammar, compile_grammar):
            retry_after = registry.try_admit(tenant)
            if retry_after is not None:
                raise RuntimeError("throttled")
            try:
                compile_grammar(grammar)
            except BaseException:
                registry.refund(tenant)
                raise
            return True
        """,
    )
    assert rule_ids(result) == []


# --------------------------------------------------------------------- TPU018


def test_tpu018_flags_yield_while_holding_lock(tmp_path):
    result = lint_source(
        tmp_path,
        """
        import threading

        class Streamer:
            def __init__(self):
                self._lock = threading.Lock()

            def stream(self, chunks):
                with self._lock:
                    for chunk in chunks:
                        yield chunk
        """,
    )
    assert "TPU018" in rule_ids(result)


def test_tpu018_near_miss_snapshot_then_yield(tmp_path):
    # copy under the lock, yield outside it — the consumer can stall forever
    # without holding up writers
    result = lint_source(
        tmp_path,
        """
        import threading

        class Streamer:
            def __init__(self):
                self._lock = threading.Lock()
                self._chunks = []

            def stream(self):
                with self._lock:
                    snapshot = list(self._chunks)
                for chunk in snapshot:
                    yield chunk
        """,
    )
    assert rule_ids(result) == []


# --------------------------------------------------------------------- TPU019


def test_tpu019_flags_early_return_leaking_handle(tmp_path):
    result = lint_source(
        tmp_path,
        """
        def read_config(path, strict):
            handle = open(path)
            if strict:
                return None
            handle.close()
            return True
        """,
    )
    assert rule_ids(result) == ["TPU019"]


def test_tpu019_near_miss_returning_the_resource_or_closing_first(tmp_path):
    # returning the handle transfers ownership to the caller; closing before
    # the early return is the fix the rule asks for
    result = lint_source(
        tmp_path,
        """
        def open_config(path, strict):
            handle = open(path)
            if strict:
                return handle
            handle.close()
            return None

        def peek_config(path, strict):
            handle = open(path)
            if strict:
                handle.close()
                return None
            handle.close()
            return True
        """,
    )
    assert rule_ids(result) == []


# --------------------------------------- TPU015 dominance of the in-body bound


def test_tpu015_in_body_bound_dominating_the_back_edge_is_clean(tmp_path):
    result = lint_source(
        tmp_path,
        """
        def reconnect(host):
            attempt = 0
            while True:
                resp = host.ping()
                if resp:
                    return resp
                if attempt >= 5:
                    raise RuntimeError("gave up")
                attempt += 1
        """,
    )
    assert rule_ids(result) == []


def test_tpu015_bound_buried_under_rare_flag_still_flags(tmp_path):
    # the bound test only runs when `flag` flips — it does not dominate the
    # back edge, so the loop is effectively unbounded
    result = lint_source(
        tmp_path,
        """
        def reconnect(host, flag):
            attempt = 0
            while True:
                resp = host.ping()
                if flag:
                    if attempt >= 5:
                        break
                attempt += 1
        """,
    )
    assert rule_ids(result) == ["TPU015"]


# ----------------------------------------------------- baseline + disable-file


def test_baseline_records_then_reports_only_new(tmp_path, capsys):
    target = tmp_path / "mod.py"
    target.write_text(
        textwrap.dedent(
            """
            import os

            A = int(os.environ.get("A", "0"))
            """
        )
    )
    baseline = tmp_path / "lint-baseline.json"
    assert (
        lint_main([str(target), "--baseline", str(baseline), "--update-baseline"]) == 0
    )
    capsys.readouterr()
    # known finding absorbed; exit 0 even though the finding still exists
    assert lint_main([str(target), "--baseline", str(baseline)]) == 0
    out = capsys.readouterr().out
    assert "0 finding(s)" in out and "1 baselined" in out
    # a NEW finding (second env read) still fails the gate
    target.write_text(target.read_text() + 'B = int(os.environ.get("B", "0"))\n')
    assert lint_main([str(target), "--baseline", str(baseline)]) == 1
    out = capsys.readouterr().out
    assert "1 finding(s)" in out and "1 baselined" in out


def test_baseline_missing_file_is_a_usage_error(tmp_path, capsys):
    target = tmp_path / "mod.py"
    target.write_text("x = 1\n")
    assert lint_main([str(target), "--baseline", str(tmp_path / "absent.json")]) == 2
    assert "does not exist" in capsys.readouterr().err


def test_baseline_sarif_carries_baseline_state(tmp_path, capsys):
    target = tmp_path / "mod.py"
    target.write_text(
        textwrap.dedent(
            """
            import os

            A = int(os.environ.get("A", "0"))
            """
        )
    )
    baseline = tmp_path / "bl.json"
    lint_main([str(target), "--baseline", str(baseline), "--update-baseline"])
    capsys.readouterr()
    target.write_text(target.read_text() + 'B = int(os.environ.get("B", "0"))\n')
    lint_main([str(target), "--baseline", str(baseline), "--format", "sarif"])
    payload = json.loads(capsys.readouterr().out)
    states = sorted(r["baselineState"] for r in payload["runs"][0]["results"])
    assert states == ["new", "unchanged"]


def test_disable_file_suppresses_both_passes(tmp_path):
    # per-file rule (TPU005) and project rule (TPU017) both honor the header
    # comment; the un-listed rule still fires
    result = lint_pkg(
        tmp_path,
        {
            "mod.py": """
            # tpu-lint: disable-file=TPU005, TPU017
            import os

            A = int(os.environ.get("A", "0"))

            def submit(registry, tenant, work):
                retry_after = registry.try_admit(tenant)
                if retry_after is not None:
                    raise RuntimeError("throttled")
                work()
                return True
            """,
        },
    )
    assert rule_ids(result) == []
    assert sorted(f.rule for f in result.suppressed) == ["TPU005", "TPU017"]


def test_disable_file_only_honored_in_first_five_lines(tmp_path):
    result = lint_source(
        tmp_path,
        """
        import os

        A = 1
        B = 2
        C = 3
        # tpu-lint: disable-file=TPU005
        D = int(os.environ.get("D", "0"))
        """,
    )
    assert rule_ids(result) == ["TPU005"]
    assert result.suppressed == []
