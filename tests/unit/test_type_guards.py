"""Table-driven signature-contract tests — mirrors reference tests/unit/test_type_guards.py."""

from typing import Any, Dict, List, NamedTuple, Optional, Tuple, Union

import pandas as pd
import pytest

from unionml_tpu import type_guards


class Estimator:
    ...


# ---------------------------------------------------------------- guard_reader


def test_guard_reader_ok():
    def reader() -> pd.DataFrame:
        ...

    type_guards.guard_reader(reader)


def test_guard_reader_missing_annotation():
    def reader():
        ...

    with pytest.raises(TypeError, match="return annotation cannot be empty"):
        type_guards.guard_reader(reader)


# ---------------------------------------------------------------- guard_loader


@pytest.mark.parametrize(
    "annotation,expected,ok",
    [
        (pd.DataFrame, pd.DataFrame, True),
        (Any, pd.DataFrame, True),
        (pd.DataFrame, Any, True),
        (Union[pd.DataFrame, str], pd.DataFrame, True),
        (str, pd.DataFrame, False),
    ],
)
def test_guard_loader(annotation, expected, ok):
    def loader(data):
        ...

    loader.__annotations__["data"] = annotation
    if ok:
        type_guards.guard_loader(loader, expected)
    else:
        with pytest.raises(TypeError):
            type_guards.guard_loader(loader, expected)


# ---------------------------------------------------------------- guard_splitter


def _valid_splitter(data: pd.DataFrame, test_size: float, shuffle: bool, random_state: int) -> Tuple[pd.DataFrame, pd.DataFrame]:
    ...


def test_guard_splitter_ok():
    type_guards.guard_splitter(_valid_splitter, pd.DataFrame, "reader")


def test_guard_splitter_namedtuple_output_ok():
    class Splits(NamedTuple):
        train: pd.DataFrame
        test: pd.DataFrame

    def splitter(data: pd.DataFrame, test_size: float, shuffle: bool, random_state: int) -> Splits:
        ...

    type_guards.guard_splitter(splitter, pd.DataFrame, "reader")


@pytest.mark.parametrize(
    "fn_src",
    [
        # wrong input type
        "def s(data: str, test_size: float, shuffle: bool, random_state: int) -> Tuple[str, str]: ...",
        # non-generic output
        "def s(data: pd.DataFrame, test_size: float, shuffle: bool, random_state: int) -> pd.DataFrame: ...",
        # output element type mismatch
        "def s(data: pd.DataFrame, test_size: float, shuffle: bool, random_state: int) -> Tuple[str, str]: ...",
        # missing canonical kwarg
        "def s(data: pd.DataFrame, test_size: float, shuffle: bool) -> Tuple[pd.DataFrame, pd.DataFrame]: ...",
        # wrongly typed canonical kwarg
        "def s(data: pd.DataFrame, test_size: str, shuffle: bool, random_state: int) -> Tuple[pd.DataFrame, pd.DataFrame]: ...",
    ],
)
def test_guard_splitter_invalid(fn_src):
    namespace = {"pd": pd, "Tuple": Tuple}
    exec(fn_src, namespace)
    with pytest.raises(TypeError):
        type_guards.guard_splitter(namespace["s"], pd.DataFrame, "reader")


# ---------------------------------------------------------------- guard_parser


def test_guard_parser_ok():
    def parser(data: pd.DataFrame, features: Optional[List[str]], targets: List[str]) -> Tuple[pd.DataFrame, pd.DataFrame]:
        ...

    type_guards.guard_parser(parser, pd.DataFrame, "reader")


def test_guard_parser_missing_kwarg():
    def parser(data: pd.DataFrame, features: Optional[List[str]]) -> Tuple[pd.DataFrame, pd.DataFrame]:
        ...

    with pytest.raises(TypeError):
        type_guards.guard_parser(parser, pd.DataFrame, "reader")


# ---------------------------------------------------------------- guard_trainer


def test_guard_trainer_ok():
    def trainer(model: Estimator, features: pd.DataFrame, target: pd.DataFrame) -> Estimator:
        ...

    type_guards.guard_trainer(trainer, Estimator, (pd.DataFrame, pd.DataFrame))


def test_guard_trainer_keyword_only_hyperparams_ok():
    def trainer(model: Estimator, features: pd.DataFrame, target: pd.DataFrame, *, lr: float = 0.1) -> Estimator:
        ...

    type_guards.guard_trainer(trainer, Estimator, (pd.DataFrame, pd.DataFrame))


@pytest.mark.parametrize(
    "model_t,data_ts,ok",
    [
        (Estimator, (pd.DataFrame, pd.DataFrame), True),
        (str, (pd.DataFrame, pd.DataFrame), False),  # wrong model type
        (Estimator, (pd.DataFrame,), False),  # arity mismatch
        (Estimator, (str, str), False),  # wrong data types
    ],
)
def test_guard_trainer_table(model_t, data_ts, ok):
    def trainer(model: Estimator, features: pd.DataFrame, target: pd.DataFrame) -> Estimator:
        ...

    if ok:
        type_guards.guard_trainer(trainer, model_t, data_ts)
    else:
        with pytest.raises(TypeError):
            type_guards.guard_trainer(trainer, model_t, data_ts)


def test_guard_trainer_return_type_mismatch():
    def trainer(model: Estimator, features: pd.DataFrame, target: pd.DataFrame) -> str:
        ...

    with pytest.raises(TypeError):
        type_guards.guard_trainer(trainer, Estimator, (pd.DataFrame, pd.DataFrame))


# ---------------------------------------------------------------- guard_evaluator


def test_guard_evaluator_ok():
    def evaluator(model: Estimator, features: pd.DataFrame, target: pd.DataFrame) -> float:
        ...

    type_guards.guard_evaluator(evaluator, Estimator, (pd.DataFrame, pd.DataFrame))


def test_guard_evaluator_bad_data_types():
    def evaluator(model: Estimator, features: int, target: int) -> float:
        ...

    with pytest.raises(TypeError):
        type_guards.guard_evaluator(evaluator, Estimator, (pd.DataFrame, pd.DataFrame))


# ---------------------------------------------------------------- guard_predictor


def test_guard_predictor_ok():
    def predictor(model: Estimator, features: pd.DataFrame) -> List[float]:
        ...

    type_guards.guard_predictor(predictor, Estimator, pd.DataFrame)


def test_guard_predictor_multiple_features_args():
    def predictor(model: Estimator, a: pd.DataFrame, b: pd.DataFrame) -> List[float]:
        ...

    with pytest.raises(TypeError, match="single 'features' argument"):
        type_guards.guard_predictor(predictor, Estimator, pd.DataFrame)


def test_guard_predictor_missing_return():
    def predictor(model: Estimator, features: pd.DataFrame):
        ...

    with pytest.raises(TypeError, match="needs a return type annotation"):
        type_guards.guard_predictor(predictor, Estimator, pd.DataFrame)


# ---------------------------------------------------------------- feature guards


def test_guard_feature_loader_arity():
    def feature_loader(a: Any, b: Any) -> pd.DataFrame:
        ...

    with pytest.raises(TypeError, match="single argument"):
        type_guards.guard_feature_loader(feature_loader, Any)


def test_guard_feature_transformer_arity():
    def feature_transformer(a: Any, b: Any) -> pd.DataFrame:
        ...

    with pytest.raises(TypeError, match="single argument"):
        type_guards.guard_feature_transformer(feature_transformer, Any)


def test_guard_feature_transformer_ok():
    def feature_transformer(features: pd.DataFrame) -> pd.DataFrame:
        ...

    type_guards.guard_feature_transformer(feature_transformer, pd.DataFrame)
