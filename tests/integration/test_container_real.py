"""Opt-in REAL-docker execution lane: the ContainerLauncher e2e with no shims.

Reference analog: the reference's integration ring boots a real Flyte sandbox
cluster behind the ``UNIONML_CI`` opt-in (reference
tests/integration/test_flyte_remote.py:17,33-57) and runs deploy→train→fetch
against it. Here the opt-in is ``UNIONML_TPU_REAL_DOCKER=1`` plus a working
docker daemon: the deployed bundle is ``docker build``-t through the real
:func:`unionml_tpu.container.build_image` (the same function deploy calls),
and ``remote_train`` runs ``job_runner`` to completion INSIDE the container
via :class:`~unionml_tpu.launcher.ContainerLauncher` — the shim ring
(test_container.py) pins the argv semantics; this ring pins that a real
daemon accepts them. Skips gracefully wherever docker is absent (including
the TPU build environment this repo is developed in), so CI without docker
stays green; a push lane would additionally need a registry server, so deploy
here runs registry-less and the image is built directly from the bundle.

Environment knobs:

- ``UNIONML_TPU_REAL_DOCKER=1`` — opt in (required).
- ``UNIONML_TPU_REAL_DOCKER_BASE`` — base image for the test Dockerfile
  (default ``python:3.12-slim``; must be pullable or already present).
"""

from __future__ import annotations

import os
import shutil
import subprocess
import sys
from pathlib import Path

import pytest

from tests.unit.test_remote import APP_SOURCE

REPO_ROOT = Path(__file__).resolve().parent.parent.parent


def _docker_usable() -> bool:
    if os.environ.get("UNIONML_TPU_REAL_DOCKER") != "1":
        return False
    if shutil.which("docker") is None:
        return False
    try:
        return (
            subprocess.run(
                ["docker", "info"], capture_output=True, timeout=30
            ).returncode
            == 0
        )
    except (OSError, subprocess.TimeoutExpired):
        return False


pytestmark = pytest.mark.skipif(
    not _docker_usable(),
    reason="real-docker lane is opt-in: set UNIONML_TPU_REAL_DOCKER=1 with a working docker daemon",
)

#: the runtime deps job_runner's import chain needs (torch/sqlalchemy/etc. are
#: lazy imports the digits app never reaches); the framework itself is
#: volume-mounted rather than copied so the lane tests the CURRENT tree
_DOCKERFILE = """\
FROM {base}
ENV PIP_NO_CACHE_DIR=1
RUN pip install --quiet "jax" flax optax orbax-checkpoint numpy pandas scikit-learn
WORKDIR /app
ENV PYTHONPATH=/app
COPY . /app
ENTRYPOINT ["python", "-m", "unionml_tpu.job_runner"]
"""


@pytest.fixture
def real_app(tmp_path, monkeypatch):
    app_dir = tmp_path / "appsrc"
    app_dir.mkdir()
    (app_dir / "remote_app.py").write_text(APP_SOURCE)
    base = os.environ.get("UNIONML_TPU_REAL_DOCKER_BASE", "python:3.12-slim")
    (app_dir / "Dockerfile").write_text(_DOCKERFILE.format(base=base))
    monkeypatch.syspath_prepend(str(app_dir))
    monkeypatch.chdir(app_dir)
    # the container has no TPU plugin; pin the forwarded JAX_* env to cpu so
    # backend init inside the container never probes an accelerator
    monkeypatch.setenv("JAX_PLATFORMS", "cpu")
    import importlib

    import remote_app

    importlib.reload(remote_app)
    return remote_app


def test_container_launcher_trains_in_a_real_container(real_app, tmp_path):
    """deploy (bundle) → real ``docker build`` → ``remote_train`` executes
    job_runner inside the container → the artifact comes back through the
    bind-mounted store with real metrics."""
    from unionml_tpu.container import build_image
    from unionml_tpu.launcher import ContainerLauncher

    store = tmp_path / "store"
    model = real_app.model
    tag = "unionml-tpu-real-lane:test"
    # the framework tree rides a read-only mount at its host path, so the
    # worker env's PYTHONPATH (bundle + framework root) resolves in-container
    launcher = ContainerLauncher(image=tag, docker_args=("-v", f"{REPO_ROOT}:{REPO_ROOT}:ro"))
    model.remote(backend_store=str(store), launcher=launcher)
    version = model.remote_deploy(app_version="real-docker-v1")
    bundle = (
        store / "unionml-tpu" / "development" / "apps" / "remote_model" / version / "bundle"
    )
    assert (bundle / "Dockerfile").exists()  # the app's file shipped with the bundle

    build_image(bundle, tag)  # the REAL build path deploy uses when a registry is set
    try:
        inspect = subprocess.run(["docker", "image", "inspect", tag], capture_output=True)
        assert inspect.returncode == 0, "built image not visible to the daemon"

        artifact = model.remote_train(hyperparameters={"max_iter": 200}, wait=True)
        assert artifact.metrics["train"] > 0.8
    finally:
        subprocess.run(["docker", "rmi", "-f", tag], capture_output=True)


def test_lane_gate_reports_skip_reason():
    """When this module RUNS, docker is genuinely usable — a canary that the
    gate itself executed (the skipif path is exercised everywhere else)."""
    assert _docker_usable()
    assert sys.version_info >= (3, 9)
