"""Live-server serving tests: a real socket, real HTTP framing.

The integration ring's analog of the reference's ``tests/integration/test_fastapi.py``
(boots ``unionml serve`` as a subprocess and polls it over HTTP, :13-26): these boot
the stdlib server on an ephemeral port in a daemon thread and speak raw HTTP to pin
the wire contracts — chunked transfer for streaming, HTTP/1.0 close-delimited
fallback, and keep-alive connection reuse. In-process route/dispatch tests stay in
tests/unit/test_serving.py.
"""

import json
import socket
import threading
import time

from unionml_tpu.serving import serving_app


def _boot(app):
    """Run the app on an ephemeral port in a daemon thread; returns (host, port).

    Daemon thread: asyncio.run(serve_forever) has no cross-thread stop; it dies
    with the test process, and nothing else in the session targets the port.
    """
    host = "127.0.0.1"
    with socket.socket() as probe_sock:  # ephemeral port: parallel runs can't collide
        probe_sock.bind((host, 0))
        port = probe_sock.getsockname()[1]
    threading.Thread(target=lambda: app.run(host=host, port=port), daemon=True).start()
    for _ in range(100):
        try:
            socket.create_connection((host, port), timeout=1).close()
            break
        except OSError:
            time.sleep(0.05)
    return host, port


def test_predict_stream_chunked_over_socket(sklearn_model):
    """The streaming route over a real socket: chunked transfer encoding, one
    ND-JSON line per yielded item, arriving as separate HTTP chunks."""
    sklearn_model.train(hyperparameters={"max_iter": 500})

    @sklearn_model.stream_predictor
    def stream_predictor(model_object, features):
        for i in range(3):
            yield {"piece": i, "rows": len(features)}

    app = serving_app(sklearn_model)
    host, port = _boot(app)

    body = json.dumps({"features": [{"x": 1.0}]}).encode()
    request = (
        f"POST /predict-stream HTTP/1.1\r\nHost: x\r\nConnection: close\r\n"
        f"Content-Length: {len(body)}\r\n\r\n"
    ).encode() + body
    with socket.create_connection((host, port), timeout=10) as sock:
        sock.sendall(request)
        raw = b""
        while True:
            data = sock.recv(65536)
            if not data:
                break
            raw += data
    headers, _, chunked = raw.partition(b"\r\n\r\n")
    assert b"Transfer-Encoding: chunked" in headers
    assert b"application/x-ndjson" in headers
    # de-chunk
    payload = b""
    rest = chunked
    while rest:
        size_line, _, rest = rest.partition(b"\r\n")
        size = int(size_line, 16)
        if size == 0:
            break
        payload, rest = payload + rest[:size], rest[size + 2 :]
    lines = [json.loads(line) for line in payload.decode().strip().split("\n")]
    assert lines == [{"piece": i, "rows": 1} for i in range(3)]


def test_predict_stream_http10_gets_unframed_body(sklearn_model):
    """HTTP/1.0 peers cannot parse chunked framing: they get raw ND-JSON bytes
    delimited by connection close."""
    sklearn_model.train(hyperparameters={"max_iter": 500})

    @sklearn_model.stream_predictor
    def stream_predictor(model_object, features):
        yield {"n": 1}
        yield {"n": 2}

    app = serving_app(sklearn_model)
    host, port = _boot(app)

    body = json.dumps({"features": [{"x": 1.0}]}).encode()
    request = (
        f"POST /predict-stream HTTP/1.0\r\nHost: x\r\nContent-Length: {len(body)}\r\n\r\n"
    ).encode() + body
    with socket.create_connection((host, port), timeout=10) as sock:
        sock.sendall(request)
        raw = b""
        while True:
            data = sock.recv(65536)
            if not data:
                break  # close-delimited
            raw += data
    headers, _, stream_body = raw.partition(b"\r\n\r\n")
    assert b"Transfer-Encoding" not in headers
    assert b"Connection: close" in headers
    lines = [json.loads(line) for line in stream_body.decode().strip().split("\n")]
    assert lines == [{"n": 1}, {"n": 2}]


def test_http_keep_alive_serves_multiple_requests_per_connection(sklearn_model):
    sklearn_model.train(hyperparameters={"max_iter": 500})
    app = serving_app(sklearn_model)
    host, port = _boot(app)

    def http_get(sock, path):
        sock.sendall(f"GET {path} HTTP/1.1\r\nHost: x\r\n\r\n".encode())
        head = b""
        while b"\r\n\r\n" not in head:
            head += sock.recv(4096)
        headers, _, rest = head.partition(b"\r\n\r\n")
        length = int([line for line in headers.split(b"\r\n") if b"content-length" in line.lower()][0].split(b":")[1])
        while len(rest) < length:
            rest += sock.recv(4096)
        return headers, rest

    # two requests down ONE connection: the first response must be keep-alive
    with socket.create_connection((host, port), timeout=5) as sock:
        headers1, _ = http_get(sock, "/health")
        assert b"Connection: keep-alive" in headers1
        headers2, body2 = http_get(sock, "/metrics")
        assert b"200 OK" in headers2.split(b"\r\n")[0]


def test_client_disconnect_releases_continuous_slot(sklearn_model):
    """A client that drops its /predict-stream connection mid-generation must
    release its ContinuousBatcher slot (the server acloses the payload, the
    route closes the predictor iterator, the engine frees the slot) — otherwise
    a single flaky client permanently burns a decode slot."""
    import jax
    import jax.numpy as jnp

    from unionml_tpu.models import GenerationConfig, Generator, Llama, LlamaConfig
    from unionml_tpu.serving import ContinuousBatcher

    config = LlamaConfig.tiny(
        vocab_size=61, dim=64, n_layers=2, n_heads=4, n_kv_heads=2, hidden_dim=128,
        dtype=jnp.float32, param_dtype=jnp.float32,
    )
    module = Llama(config)
    params = module.init(jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))["params"]
    gen = Generator(
        module, params,
        GenerationConfig(max_new_tokens=512, temperature=0.0, prompt_buckets=(16,)),
    )
    batcher = ContinuousBatcher(gen, slots=1, decode_chunk=2)

    sklearn_model.train(hyperparameters={"max_iter": 200})

    @sklearn_model.stream_predictor
    def stream_predictor(model_object, features):
        for chunk in batcher.submit([3, 1, 4, 1, 5]):
            yield chunk.tolist()

    sklearn_model.generation_batcher = batcher
    app = serving_app(sklearn_model)
    host, port = _boot(app)
    try:
        body = json.dumps({"features": [{"x": 1.0}]}).encode()
        request = (
            f"POST /predict-stream HTTP/1.1\r\nHost: x\r\nConnection: close\r\n"
            f"Content-Length: {len(body)}\r\n\r\n"
        ).encode() + body
        sock = socket.create_connection((host, port), timeout=10)
        sock.sendall(request)
        sock.recv(4096)  # headers + first chunk(s): generation is underway
        assert batcher.stats()["resident"] == 1
        sock.close()  # client walks away mid-stream (budget 512 ~= forever)

        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            if batcher.stats()["resident"] == 0:
                break
            time.sleep(0.2)
        assert batcher.stats()["resident"] == 0, "slot leaked after disconnect"
        # the freed slot admits new work
        out = list(batcher.submit([9, 2], max_new_tokens=4))
        assert sum(len(c) for c in out) == 4
    finally:
        batcher.close()
