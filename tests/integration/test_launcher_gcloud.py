"""TPUVMLauncher's REAL gcloud code path, driven through a shim ``gcloud`` on PATH.

The happy-path Launcher-interface test (test_remote.py) injects Python fakes and
never executes ``_gcloud_provision``/``_gcloud_ssh``/``_gcloud_delete``. This
ring is the analog of the reference's sandbox-backed remote tests
(/root/reference/tests/integration/test_flyte_remote.py:33-79): a shim gcloud
binary records every invocation and — for ``ssh`` — actually EXECUTES the
``--command`` locally, so a full remote_train runs end-to-end through the
default transport. Failure injection (env-controlled) covers the paths the
VERDICT called out: provision failure (with partial-node cleanup), ssh/worker
failure (watchdog resubmit reusing the provisioned node), and teardown failure
(node stays registered for a retry instead of leaking).
"""

import os
import subprocess
import textwrap

import pytest

from tests.unit.test_remote import APP_SOURCE

_SHIM = textwrap.dedent(
    """\
    #!/usr/bin/env bash
    # gcloud shim: logs every call; behavior injected via GCLOUD_* env vars.
    echo "$*" >> "$GCLOUD_SHIM_LOG"
    verb="$4"  # gcloud compute tpus tpu-vm <verb> ... ($0 is gcloud itself)
    case "$verb" in
      create)
        if [ -n "$GCLOUD_FAIL_CREATE_ONCE" ] && [ ! -f "$GCLOUD_SHIM_STATE/create_failed" ]; then
          mkdir -p "$GCLOUD_SHIM_STATE"; touch "$GCLOUD_SHIM_STATE/create_failed"
          echo "ERROR: quota exceeded" >&2; exit 1
        fi
        exit 0 ;;
      ssh)
        cmd=""; worker=""; prev=""
        for a in "$@"; do
          [ "$prev" = "--command" ] && cmd="$a"
          case "$a" in --worker=*) worker="${a#--worker=}";; esac
          prev="$a"
        done
        if [ -n "$GCLOUD_FAIL_SSH_ONCE" ] && [ ! -f "$GCLOUD_SHIM_STATE/ssh_failed" ]; then
          mkdir -p "$GCLOUD_SHIM_STATE"; touch "$GCLOUD_SHIM_STATE/ssh_failed"
          echo "ssh: connection refused (worker $worker)" >&2; exit 255
        fi
        exec bash -c "$cmd" ;;
      delete)
        if [ -n "$GCLOUD_FAIL_DELETE" ]; then echo "ERROR: delete failed" >&2; exit 1; fi
        exit 0 ;;
    esac
    exit 0
    """
)

# Logged lines are "$*" (argv without $0): 'compute tpus tpu-vm <verb> <node> ...'
# -> verb at split()[3], node at split()[4]. Pinned by test_shim_parses_verbs.


@pytest.fixture
def gcloud_env(tmp_path, monkeypatch):
    """A shim gcloud on PATH + call log + state dir; returns helpers."""
    bin_dir = tmp_path / "shimbin"
    bin_dir.mkdir()
    shim = bin_dir / "gcloud"
    shim.write_text(_SHIM)
    shim.chmod(0o755)
    log = tmp_path / "gcloud_calls.log"
    log.write_text("")
    state = tmp_path / "shim_state"
    monkeypatch.setenv("PATH", f"{bin_dir}{os.pathsep}{os.environ['PATH']}")
    monkeypatch.setenv("GCLOUD_SHIM_LOG", str(log))
    monkeypatch.setenv("GCLOUD_SHIM_STATE", str(state))
    for var in ("GCLOUD_FAIL_CREATE_ONCE", "GCLOUD_FAIL_SSH_ONCE", "GCLOUD_FAIL_DELETE"):
        monkeypatch.delenv(var, raising=False)

    def calls(verb=None):
        lines = [ln for ln in log.read_text().splitlines() if ln]
        if verb is None:
            return lines
        return [ln for ln in lines if ln.split()[3] == verb]

    return calls


@pytest.fixture
def gcloud_app(tmp_path, monkeypatch):
    """The standard remote test app, backed by a file store under tmp_path."""
    app_dir = tmp_path / "appsrc"
    app_dir.mkdir()
    (app_dir / "remote_app.py").write_text(APP_SOURCE)
    monkeypatch.syspath_prepend(str(app_dir))
    monkeypatch.chdir(app_dir)
    import importlib

    import remote_app

    importlib.reload(remote_app)
    return remote_app


def test_shim_parses_verbs(gcloud_env, tmp_path):
    """Sanity-pin the shim's argv layout against the launcher's command shape."""
    subprocess.run(
        ["gcloud", "compute", "tpus", "tpu-vm", "create", "n1", "--accelerator-type=v5e-8"],
        check=True,
    )
    out = subprocess.run(
        ["gcloud", "compute", "tpus", "tpu-vm", "ssh", "n1", "--worker=0", "--command", "echo shim-ok"],
        check=True, stdout=subprocess.PIPE, text=True,
    )
    assert out.stdout.strip() == "shim-ok"
    assert [ln.split()[3] for ln in gcloud_env()] == ["create", "ssh"]


def test_default_gcloud_path_trains_end_to_end(gcloud_env, gcloud_app, tmp_path):
    """remote_train through the DEFAULT provisioner/transport: the shim executes
    the ssh --command locally, so the worker really trains; create/ssh argv
    carry the accelerator, version, project/zone, and worker index."""
    from unionml_tpu.launcher import TPUVMLauncher

    launcher = TPUVMLauncher(project="proj-1", zone="us-central2-b")
    model = gcloud_app.model
    model.remote(backend_store=str(tmp_path / "store"), accelerator="v5e-8", launcher=launcher)
    model.remote_deploy(app_version="gcloud-v1")
    artifact = model.remote_train(hyperparameters={"max_iter": 200}, wait=True)
    assert artifact.metrics["train"] > 0.8

    creates, sshes = gcloud_env("create"), gcloud_env("ssh")
    assert len(creates) == 1 and len(sshes) == 1
    assert "--accelerator-type=v5e-8" in creates[0]
    assert "--version=tpu-ubuntu2204-base" in creates[0]
    assert "--project proj-1" in creates[0] and "--zone us-central2-b" in creates[0]
    assert "--worker=0" in sshes[0]

    # teardown deletes the node it created
    execution_path = list(launcher._nodes)[0]
    launcher.teardown(execution_path)
    deletes = gcloud_env("delete")
    assert len(deletes) == 1 and "--quiet" in deletes[0]
    assert launcher._nodes == {}


def test_provision_failure_cleans_up_and_retry_reprovisions(gcloud_env, gcloud_app, tmp_path, monkeypatch):
    """A failed create surfaces as a launch failure AFTER a best-effort delete of
    the possibly-half-created node; nothing is cached, so the next attempt
    provisions from scratch and succeeds."""
    from unionml_tpu.launcher import TPUVMLauncher

    monkeypatch.setenv("GCLOUD_FAIL_CREATE_ONCE", "1")
    launcher = TPUVMLauncher()
    model = gcloud_app.model
    model.remote(backend_store=str(tmp_path / "store"), accelerator="v5e-8", launcher=launcher)
    model.remote_deploy(app_version="gcloud-v2")

    with pytest.raises(RuntimeError, match="provisioning TPU slice"):
        model.remote_train(hyperparameters={"max_iter": 200}, wait=True)
    assert launcher._nodes == {}  # no broken node cached
    # the failed create was followed by a cleanup delete of the same node
    assert len(gcloud_env("create")) == 1
    assert len(gcloud_env("delete")) == 1
    assert gcloud_env("create")[0].split()[4] == gcloud_env("delete")[0].split()[4]

    # retry: shim now succeeds; training completes through a fresh node
    artifact = model.remote_train(hyperparameters={"max_iter": 200}, wait=True)
    assert artifact.metrics["train"] > 0.8
    assert len(gcloud_env("create")) == 2


def test_ssh_failure_consumes_retry_and_reuses_node(gcloud_env, gcloud_app, tmp_path, monkeypatch):
    """A dead ssh transport (exit 255) is a dead worker to the watchdog: with
    retries=1 the execution resubmits THROUGH THE SAME provisioned node (exactly
    one create; two ssh attempts) and completes."""
    from unionml_tpu.launcher import TPUVMLauncher

    monkeypatch.setenv("GCLOUD_FAIL_SSH_ONCE", "1")
    launcher = TPUVMLauncher()
    model = gcloud_app.model
    model.remote(backend_store=str(tmp_path / "store"), accelerator="v5e-8", launcher=launcher)
    model.remote_deploy(app_version="gcloud-v3")
    artifact = model.remote_train(hyperparameters={"max_iter": 200}, wait=True, retries=1)
    assert artifact.metrics["train"] > 0.8
    assert len(gcloud_env("create")) == 1  # resubmit reused the slice
    assert len(gcloud_env("ssh")) == 2


def test_teardown_failure_keeps_node_registered_for_retry(gcloud_env, gcloud_app, tmp_path, monkeypatch):
    from unionml_tpu.launcher import TPUVMLauncher

    launcher = TPUVMLauncher()
    model = gcloud_app.model
    model.remote(backend_store=str(tmp_path / "store"), accelerator="v5e-8", launcher=launcher)
    model.remote_deploy(app_version="gcloud-v4")
    model.remote_train(hyperparameters={"max_iter": 200}, wait=True)
    execution_path = list(launcher._nodes)[0]
    node = launcher._nodes[execution_path]

    monkeypatch.setenv("GCLOUD_FAIL_DELETE", "1")
    with pytest.raises(RuntimeError, match="deleting TPU slice"):
        launcher.teardown(execution_path)
    assert launcher._nodes == {execution_path: node}  # NOT silently leaked

    monkeypatch.delenv("GCLOUD_FAIL_DELETE")
    launcher.teardown(execution_path)  # retry succeeds
    assert launcher._nodes == {}
