"""CLI-booted live server: the analog of the reference's subprocess-serve test
(/root/reference/tests/integration/test_fastapi.py:13-26) — ``unionml-tpu serve``
runs as a real subprocess and is polled over real HTTP."""

import contextlib
import json
import os
import pathlib
import socket
import subprocess
import sys
import time
import urllib.request

import pytest


@contextlib.contextmanager
def _served(args, cwd, env, log_path, startup_s):
    """Boot ``unionml-tpu serve`` as a subprocess, poll ``/health`` to a
    wall-clock deadline, yield the base URL, and tear down. Logs go to a FILE:
    an unread ``stdout=PIPE`` fills its 64KB buffer during a chatty warmup and
    blocks the server before it ever binds (observed live with the generation
    template)."""
    with socket.socket() as probe:
        probe.bind(("127.0.0.1", 0))
        port = probe.getsockname()[1]
    with open(log_path, "wb") as server_log:
        proc = subprocess.Popen(
            [sys.executable, "-m", "unionml_tpu.cli", "serve", *args, "--port", str(port)],
            cwd=cwd,
            env=env,
            stdout=server_log,
            stderr=subprocess.STDOUT,
        )
        try:
            base = f"http://127.0.0.1:{port}"
            deadline = time.monotonic() + startup_s
            while True:
                if proc.poll() is not None:
                    raise AssertionError(f"server exited rc={proc.returncode}")
                try:
                    with urllib.request.urlopen(base + "/health", timeout=1):
                        break
                except Exception:
                    if time.monotonic() > deadline:
                        tail = pathlib.Path(log_path).read_bytes()[-1500:]
                        raise AssertionError(
                            f"server did not come up in {startup_s}s; log tail: "
                            + tail.decode(errors="replace")
                        )
                    time.sleep(0.2)
            yield base
        finally:
            proc.terminate()
            try:
                proc.wait(timeout=10)
            except subprocess.TimeoutExpired:
                # a server blocked in native XLA compile ignores SIGTERM; a
                # TimeoutExpired here would mask the diagnostic AssertionError
                # and leak the process + port for the rest of the run
                proc.kill()
                proc.wait()


def test_serve_workers_flag_boots_multiprocess_server(cli_project, tmp_path):
    """--workers 2: the port is shared via SO_REUSEPORT and requests succeed
    (reference serve clones uvicorn's full CLI incl. --workers, cli.py:172-205)."""
    import cli_app

    cli_app.model.train(hyperparameters={"max_iter": 500})
    model_file = cli_project / "model.joblib"
    cli_app.model.save(str(model_file))

    serve_args = [
        "cli_app:model", "--model-path", str(model_file), "--workers", "2",
        "--log-level", "info",
    ]
    with _served(serve_args, cli_project, dict(os.environ), tmp_path / "server.log", 60) as base:
        body = json.dumps({"features": [{"x0": 1.0, "x1": 2.0}]}).encode()
        for _ in range(4):  # several requests; kernel may spread them over workers
            req = urllib.request.Request(
                base + "/predict", data=body, headers={"Content-Type": "application/json"}
            )
            with urllib.request.urlopen(req, timeout=10) as resp:
                assert resp.status == 200
                assert len(json.loads(resp.read())) == 1


@pytest.mark.slow  # subprocess train + serve boot, ~19s; the same stack is
# covered in-process by test_templates.py's text-generation end-to-end test
def test_serve_text_generation_template_with_grammar(tmp_path):
    """The full generation stack through the CLI: render the text-generation
    template, train + save in a subprocess, boot ``unionml-tpu serve``, and
    stream a grammar-prefixed prompt over real HTTP — the '@word' continuation
    must satisfy its regex (device-side token-DFA masking end to end)."""
    import re

    from unionml_tpu.templating import render_template

    project = render_template("text-generation", "genapp", tmp_path, git_init=False)
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    # REPLACE PYTHONPATH (don't prepend): the ambient path carries the axon
    # plugin site, which wins over JAX_PLATFORMS=cpu and hangs the subprocess
    # on a wedged tunnel at backend init — this ring is CPU-substrate
    env["PYTHONPATH"] = str(pathlib.Path(__file__).resolve().parents[2])
    train = subprocess.run(
        [
            sys.executable,
            "-c",
            "import jax; jax.config.update('jax_platforms', 'cpu');"
            "import app; app.model.train(hyperparameters={'learning_rate': 3e-3});"
            "app.model.save('model_object.ckpt')",
        ],
        cwd=project,
        env=env,
        capture_output=True,
        text=True,
        timeout=600,
    )
    assert train.returncode == 0, train.stderr[-2000:]

    # startup runs generation_warmup (AOT-compiles every prefill bucket + the
    # batcher's decode programs) before binding: minutes on a slow CPU host
    serve_args = ["app:model", "--model-path", str(project / "model_object.ckpt")]
    with _served(serve_args, project, env, tmp_path / "server.log", 600) as base:
        body = json.dumps({"features": ["@word the quick brown "]}).encode()
        req = urllib.request.Request(
            base + "/predict-stream", data=body, headers={"Content-Type": "application/json"}
        )
        with urllib.request.urlopen(req, timeout=120) as resp:
            assert resp.status == 200
            pieces = [json.loads(ln)[0] for ln in resp.read().decode().strip().splitlines()]
        text = "".join(pieces)
        assert text and re.fullmatch(r"[a-z]+", text), text
