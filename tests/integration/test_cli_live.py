"""CLI-booted live server: the analog of the reference's subprocess-serve test
(/root/reference/tests/integration/test_fastapi.py:13-26) — ``unionml-tpu serve``
runs as a real subprocess and is polled over real HTTP."""

import json
import os
import socket
import subprocess
import sys
import time
import urllib.request


def test_serve_workers_flag_boots_multiprocess_server(cli_project, tmp_path):
    """--workers 2: the port is shared via SO_REUSEPORT and requests succeed
    (reference serve clones uvicorn's full CLI incl. --workers, cli.py:172-205)."""
    import cli_app

    cli_app.model.train(hyperparameters={"max_iter": 500})
    model_file = cli_project / "model.joblib"
    cli_app.model.save(str(model_file))

    with socket.socket() as probe:
        probe.bind(("127.0.0.1", 0))
        port = probe.getsockname()[1]

    env = dict(os.environ)
    proc = subprocess.Popen(
        [
            sys.executable, "-m", "unionml_tpu.cli", "serve", "cli_app:model",
            "--model-path", str(model_file), "--port", str(port),
            "--workers", "2", "--log-level", "info",
        ],
        cwd=cli_project,
        env=env,
        stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT,
    )
    try:
        base = f"http://127.0.0.1:{port}"
        for _ in range(150):
            try:
                with urllib.request.urlopen(base + "/health", timeout=1):
                    break
            except Exception:
                time.sleep(0.2)
        else:
            raise AssertionError("server did not come up")
        body = json.dumps({"features": [{"x0": 1.0, "x1": 2.0}]}).encode()
        for _ in range(4):  # several requests; kernel may spread them over workers
            req = urllib.request.Request(
                base + "/predict", data=body, headers={"Content-Type": "application/json"}
            )
            with urllib.request.urlopen(req, timeout=10) as resp:
                assert resp.status == 200
                assert len(json.loads(resp.read())) == 1
    finally:
        proc.terminate()
        proc.wait(timeout=10)
