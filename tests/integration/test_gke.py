"""GKELauncher's REAL kubectl code path, driven through a shim ``kubectl`` on PATH.

The manifest emitter is unit-tested in tests/unit/test_gke.py; this ring is the
cluster analog of the gcloud/docker shim e2es (test_launcher_gcloud.py,
test_container.py): a shim kubectl records every invocation and — for ``apply`` —
actually EXECUTES the Job's workers locally (one ``unionml_tpu.job_runner``
process per completion index, env from the manifest), so a full remote_train runs
end-to-end through apply -> pod-status polling -> log streaming -> delete.
Failure injection covers worker failure (watchdog resubmit under a fresh
per-attempt job name) and apply failure.
"""

import json
import os
import subprocess
import textwrap
import time

import pytest

from tests.unit.test_remote import APP_SOURCE

_SHIM = textwrap.dedent(
    '''\
    #!/usr/bin/env python3
    # kubectl shim: logs every call; `apply` runs the Job's workers as local
    # processes (the pod analog), `get` reports their status as pod/job JSON,
    # `delete` kills them. Failure injection via KUBECTL_* env vars.
    import glob, json, os, signal, subprocess, sys

    STATE = os.environ["KUBECTL_SHIM_STATE"]
    args = sys.argv[1:]
    with open(os.environ["KUBECTL_SHIM_LOG"], "a") as fh:
        fh.write(" ".join(args) + "\\n")

    def jdir(name):
        return os.path.join(STATE, name)

    def completions(name):
        with open(os.path.join(jdir(name), "manifest.json")) as fh:
            manifest = json.load(fh)
        job = next(i for i in manifest["items"] if i["kind"] == "Job")
        return job["spec"]["completions"]

    verb = args[0]
    if verb == "apply":
        if os.environ.get("KUBECTL_FAIL_APPLY"):
            print("error: connection refused", file=sys.stderr)
            sys.exit(1)
        manifest = json.loads(sys.stdin.read())
        job = next(i for i in manifest["items"] if i["kind"] == "Job")
        name = job["metadata"]["name"]
        os.makedirs(jdir(name), exist_ok=True)
        with open(os.path.join(jdir(name), "manifest.json"), "w") as fh:
            json.dump(manifest, fh)
        container = job["spec"]["template"]["spec"]["containers"][0]
        fail_first = os.environ.get("KUBECTL_FAIL_WORKER_ONCE") and name.endswith("-a0")
        for i in range(job["spec"]["completions"]):
            env = dict(os.environ)
            for entry in container["env"]:
                if "value" in entry:
                    env[entry["name"]] = entry["value"]
            # the cluster provides these: completion index -> process id, and
            # the coordinator's pod DNS name -> loopback (same port)
            env["UNIONML_TPU_PROCESS_ID"] = str(i)
            coord = env.get("UNIONML_TPU_COORDINATOR")
            if coord:
                env["UNIONML_TPU_COORDINATOR"] = "127.0.0.1:" + coord.rpartition(":")[2]
            log = os.path.join(jdir(name), "w%d.log" % i)
            rc = os.path.join(jdir(name), "w%d.rc" % i)
            body = "exit 7" if fail_first else "%s -m unionml_tpu.job_runner %s" % (
                json.dumps(sys.executable), json.dumps(container["args"][0])
            )
            cmd = "(%s) > %s 2>&1; echo $? > %s" % (body, json.dumps(log), json.dumps(rc))
            # fully detach stdio: the worker would otherwise inherit apply's
            # stdout pipe and the launcher's capture_output read would block
            # until the WORKER exits, serializing the whole "cluster"
            proc = subprocess.Popen(
                ["bash", "-c", cmd], env=env, start_new_session=True,
                stdin=subprocess.DEVNULL, stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
            )
            with open(os.path.join(jdir(name), "w%d.pid" % i), "w") as fh:
                fh.write(str(proc.pid))
        print("service/%s created\\njob.batch/%s created" % (name, name))
    elif verb == "get":
        kind = args[1]
        if kind == "pods":
            name = args[args.index("-l") + 1].split("=", 1)[1]
            items = []
            if os.path.isdir(jdir(name)):
                for i in range(completions(name)):
                    rcf = os.path.join(jdir(name), "w%d.rc" % i)
                    if os.path.exists(rcf):
                        with open(rcf) as fh:
                            phase = "Succeeded" if fh.read().strip() == "0" else "Failed"
                    else:
                        phase = "Running"
                    items.append({
                        "metadata": {
                            "name": "%s-%d" % (name, i),
                            "annotations": {"batch.kubernetes.io/job-completion-index": str(i)},
                        },
                        "status": {"phase": phase},
                    })
            print(json.dumps({"items": items}))
        else:
            name = args[2]
            if not os.path.isdir(jdir(name)):
                print("jobs.batch %s not found" % name, file=sys.stderr)
                sys.exit(1)
            rcs = []
            for i in range(completions(name)):
                rcf = os.path.join(jdir(name), "w%d.rc" % i)
                if os.path.exists(rcf):
                    with open(rcf) as fh:
                        rcs.append(fh.read().strip())
            conditions = []
            if any(rc != "0" for rc in rcs):
                conditions = [{"type": "Failed", "status": "True"}]
            elif len(rcs) == completions(name):
                conditions = [{"type": "Complete", "status": "True"}]
            print(json.dumps({"status": {"conditions": conditions}}))
    elif verb == "logs":
        follow = args[1] == "-f"
        pod = args[2] if follow else args[1]
        name, index = pod.rsplit("-", 1)
        path = os.path.join(jdir(name), "w%s.log" % index)
        open(path, "a").close()
        if follow:
            os.execvp("tail", ["tail", "-F", "-n", "+1", path])
        with open(path) as fh:  # terminated-pod snapshot: full output
            sys.stdout.write(fh.read())
    elif verb == "delete":
        if os.environ.get("KUBECTL_FAIL_DELETE"):
            print("error: forbidden", file=sys.stderr)
            sys.exit(1)
        kind, name = args[1], args[2]
        if kind == "job":  # a service delete must NOT kill the job's workers
            for pidf in glob.glob(os.path.join(jdir(name), "w*.pid")):
                with open(pidf) as fh:
                    try:
                        os.killpg(int(fh.read().strip()), signal.SIGKILL)
                    except (ProcessLookupError, PermissionError, ValueError):
                        pass
        print('%s "%s" deleted' % (kind, name))
    '''
)


@pytest.fixture
def kubectl_env(tmp_path, monkeypatch):
    """A shim kubectl on PATH + call log + state dir; returns the call-log reader."""
    bin_dir = tmp_path / "shimbin"
    bin_dir.mkdir()
    shim = bin_dir / "kubectl"
    shim.write_text(_SHIM)
    shim.chmod(0o755)
    log = tmp_path / "kubectl_calls.log"
    log.write_text("")
    state = tmp_path / "shim_state"
    state.mkdir()
    monkeypatch.setenv("PATH", f"{bin_dir}{os.pathsep}{os.environ['PATH']}")
    monkeypatch.setenv("KUBECTL_SHIM_LOG", str(log))
    monkeypatch.setenv("KUBECTL_SHIM_STATE", str(state))
    for var in ("KUBECTL_FAIL_APPLY", "KUBECTL_FAIL_WORKER_ONCE", "KUBECTL_FAIL_DELETE"):
        monkeypatch.delenv(var, raising=False)

    def calls(verb=None):
        lines = [ln for ln in log.read_text().splitlines() if ln]
        if verb is None:
            return lines
        return [ln for ln in lines if ln.split()[0] == verb]

    calls.state = state
    return calls


@pytest.fixture
def gke_app(tmp_path, monkeypatch):
    app_dir = tmp_path / "appsrc"
    app_dir.mkdir()
    (app_dir / "remote_app.py").write_text(APP_SOURCE)
    monkeypatch.syspath_prepend(str(app_dir))
    monkeypatch.chdir(app_dir)
    import importlib

    import remote_app

    importlib.reload(remote_app)
    return remote_app


def make_launcher():
    from unionml_tpu.gke import GKELauncher

    # fast polling (the shim is local); image override = the ContainerLauncher
    # pattern for clusters whose image is prebuilt rather than deploy-pushed
    return GKELauncher(poll_throttle_s=0.05, image="local/gke-app:test")


def applied_manifest(calls, index=0):
    state = calls.state
    jobs = sorted(p for p in state.iterdir() if p.is_dir())
    return json.loads((jobs[index] / "manifest.json").read_text())


def test_gke_job_trains_end_to_end(kubectl_env, gke_app, tmp_path):
    """remote_train through apply -> indexed pod polling -> completion: the shim
    executes the Job's worker locally, so the applied manifest IS the execution
    vehicle; the manifest carries the TPU selectors and the job_runner args."""
    model = gke_app.model
    model.remote(backend_store=str(tmp_path / "store"), accelerator="v5e-8", launcher=make_launcher())
    model.remote_deploy(app_version="gke-v1")
    artifact = model.remote_train(hyperparameters={"max_iter": 200}, wait=True)
    assert artifact.metrics["train"] > 0.8

    assert len(kubectl_env("apply")) == 1
    manifest = applied_manifest(kubectl_env)
    job = next(i for i in manifest["items"] if i["kind"] == "Job")
    pod = job["spec"]["template"]["spec"]
    assert pod["nodeSelector"]["cloud.google.com/gke-tpu-accelerator"] == "tpu-v5-lite-podslice"
    assert pod["nodeSelector"]["cloud.google.com/gke-tpu-topology"] == "2x4"
    assert pod["containers"][0]["image"] == "local/gke-app:test"
    # the worker really ran job_runner against the shared store: its execution
    # path is the args, and the pod status was polled to completion
    assert pod["containers"][0]["args"][0].startswith(str(tmp_path / "store"))
    assert kubectl_env("get")


def test_worker_logs_stream_into_execution_dir(kubectl_env, gke_app, tmp_path):
    """The handle's `kubectl logs -f` pipes the worker pod's output into the
    execution's logs.txt — the file `unionml logs` and the failure tail read."""
    model = gke_app.model
    model.remote(backend_store=str(tmp_path / "store"), accelerator="v5e-8", launcher=make_launcher())
    model.remote_deploy(app_version="gke-v2")
    model.remote_train(hyperparameters={"max_iter": 200}, wait=True)
    store = tmp_path / "store"
    logs = [p for p in store.rglob("logs.txt") if "executions" in p.parts]
    assert logs
    # the terminal snapshot (_finalize_logs) lands synchronously at the poll
    # that saw completion, so the worker's start line is here by the time wait
    # returns even if the -f streamer lost the race
    assert "job_runner: train" in logs[0].read_text()


def test_worker_failure_resubmits_under_fresh_job_name(kubectl_env, gke_app, tmp_path, monkeypatch):
    """A failed worker pod is a dead worker to the watchdog: with retries=1 the
    execution resubmits as a NEW job (per-attempt name — k8s would reject a
    create under the still-terminating old name) after deleting the failed one."""
    monkeypatch.setenv("KUBECTL_FAIL_WORKER_ONCE", "1")
    model = gke_app.model
    model.remote(backend_store=str(tmp_path / "store"), accelerator="v5e-8", launcher=make_launcher())
    model.remote_deploy(app_version="gke-v3")
    artifact = model.remote_train(hyperparameters={"max_iter": 200}, wait=True, retries=1)
    assert artifact.metrics["train"] > 0.8

    applies = kubectl_env("apply")
    assert len(applies) == 2
    names = sorted(p.name for p in kubectl_env.state.iterdir())
    assert names[0].endswith("-a0") and names[1].endswith("-a1")
    # an already-dead worker needs no kill, so the failed JOB is not deleted —
    # it stays for inspection and the manifest's ttlSecondsAfterFinished GCs it
    # (terminal polls do reap the coordinator Service, which has no TTL)
    assert not [d for d in kubectl_env("delete") if d.split()[1] == "job"]


def test_kill_deletes_the_job(kubectl_env, tmp_path):
    """The handle's kill() must target the JOB (the ContainerHandle.kill
    principle, launcher.py:159-165): pods the watchdog abandons would otherwise
    keep mutating the shared store."""
    from unionml_tpu.gke import GKELauncher, _GKEWorkerHandle

    launcher = GKELauncher(poll_throttle_s=0.05, image="x:y")
    handle = _GKEWorkerHandle(launcher, "unionml-kill-test-a0", 0, tmp_path / "logs.txt", "w")
    handle.kill()
    assert handle.returncode == -9
    job_deletes = [d for d in kubectl_env("delete") if d.split()[1] == "job"]
    assert len(job_deletes) == 1 and "unionml-kill-test-a0" in job_deletes[0]
    assert "--wait=false" in job_deletes[0]
    # the job's coordinator Service is reaped alongside it
    assert any(d.split()[1] == "service" for d in kubectl_env("delete"))


def test_multihost_slice_forms_one_distributed_runtime(kubectl_env, gke_app, tmp_path):
    """v5e-16 = 2 hosts -> a 2-completion Indexed Job. The shim plays the
    cluster: completion index -> process id, coordinator DNS -> loopback (same
    port). Both 'pods' must join ONE jax.distributed runtime (job_runner logs
    the join with its process rank) and the execution completes through the
    shared store — the emulated-cluster analog of tests/emulated/test_multihost."""
    model = gke_app.model
    model.remote(backend_store=str(tmp_path / "store"), accelerator="v5e-16", launcher=make_launcher())
    model.remote_deploy(app_version="gke-v5")
    artifact = model.remote_train(hyperparameters={"max_iter": 200}, wait=True)
    assert artifact.metrics["train"] > 0.8

    job = next(i for i in applied_manifest(kubectl_env)["items"] if i["kind"] == "Job")
    assert job["spec"]["completions"] == 2
    store = tmp_path / "store"
    logs = sorted(p for p in store.rglob("logs*.txt") if "executions" in p.parts)
    texts = " ".join(p.read_text() for p in logs)
    assert "process 0/2" in texts and "process 1/2" in texts


def test_apply_failure_raises(kubectl_env, gke_app, tmp_path, monkeypatch):
    monkeypatch.setenv("KUBECTL_FAIL_APPLY", "1")
    model = gke_app.model
    model.remote(backend_store=str(tmp_path / "store"), accelerator="v5e-8", launcher=make_launcher())
    model.remote_deploy(app_version="gke-v4")
    with pytest.raises(RuntimeError, match="kubectl apply"):
        model.remote_train(hyperparameters={"max_iter": 200}, wait=True)
