"""Per-app container images through a shim ``docker`` on PATH.

Same pattern as the shim-gcloud launcher ring: the REAL
``unionml_tpu.container`` CLI shell-outs execute against a fake binary that logs
its argv, so deploy-time image semantics (reference remote.py:60-108 parity:
registry-gated build+push, patch skips image work, bundle-as-context, generated
default Dockerfile, failure propagation) are pinned without a docker daemon.
"""

import json
import os
import subprocess
import textwrap

import pytest

from tests.unit.test_remote import APP_SOURCE

_SHIM = textwrap.dedent(
    """\
    #!/usr/bin/env bash
    echo "$*" >> "$DOCKER_SHIM_LOG"
    verb="$1"
    if [ "$verb" = "build" ] && [ -n "$DOCKER_FAIL_BUILD" ]; then
      echo "ERROR: build failed" >&2; exit 1
    fi
    if [ "$verb" = "push" ] && [ -n "$DOCKER_FAIL_PUSH" ]; then
      echo "ERROR: denied" >&2; exit 1
    fi
    if [ "$verb" = "kill" ] && [ -n "$DOCKER_FAIL_KILL" ]; then
      echo "ERROR: no such container" >&2; exit 1
    fi
    if [ "$verb" = "run" ]; then
      # EXECUTE the container locally (the gcloud-shim ssh pattern): the image's
      # entrypoint is `python -m unionml_tpu.job_runner`, its argument rides the
      # docker argv, and -e vars become the process env — so a full remote_train
      # really runs through ContainerLauncher's code path.
      if [ -n "$DOCKER_FAIL_RUN_ONCE" ] && [ ! -f "$DOCKER_SHIM_STATE/run_failed" ]; then
        mkdir -p "$DOCKER_SHIM_STATE"; touch "$DOCKER_SHIM_STATE/run_failed"
        echo "docker: container exited unexpectedly" >&2; exit 125
      fi
      shift
      envs=(); pos=()
      while [ $# -gt 0 ]; do
        case "$1" in
          -e) envs+=("$2"); shift 2;;
          -v|--name|--network) shift 2;;
          --rm) shift;;
          *) pos+=("$1"); shift;;
        esac
      done
      exec env "${envs[@]}" "$PYTHON_FOR_SHIM" -m unionml_tpu.job_runner "${pos[1]}"
    fi
    exit 0
    """
)


@pytest.fixture
def docker_env(tmp_path, monkeypatch):
    bin_dir = tmp_path / "shimbin"
    bin_dir.mkdir()
    shim = bin_dir / "docker"
    shim.write_text(_SHIM)
    shim.chmod(0o755)
    log = tmp_path / "docker_calls.log"
    log.write_text("")
    monkeypatch.setenv("PATH", f"{bin_dir}{os.pathsep}{os.environ['PATH']}")
    monkeypatch.setenv("DOCKER_SHIM_LOG", str(log))
    monkeypatch.setenv("DOCKER_SHIM_STATE", str(tmp_path / "shim_state"))
    import sys as _sys

    monkeypatch.setenv("PYTHON_FOR_SHIM", _sys.executable)
    for var in ("DOCKER_FAIL_BUILD", "DOCKER_FAIL_PUSH", "DOCKER_FAIL_RUN_ONCE", "DOCKER_FAIL_KILL"):
        monkeypatch.delenv(var, raising=False)

    def calls(verb=None):
        lines = [ln for ln in log.read_text().splitlines() if ln]
        return lines if verb is None else [ln for ln in lines if ln.split()[0] == verb]

    return calls


@pytest.fixture
def docker_app(tmp_path, monkeypatch):
    app_dir = tmp_path / "appsrc"
    app_dir.mkdir()
    (app_dir / "remote_app.py").write_text(APP_SOURCE)
    monkeypatch.syspath_prepend(str(app_dir))
    monkeypatch.chdir(app_dir)
    import importlib

    import remote_app

    importlib.reload(remote_app)
    return remote_app


def test_image_fqn_parity():
    from unionml_tpu.container import image_fqn

    # reference convention (remote.py:60-66): registry/name:model-version, _ -> -
    assert image_fqn("my_model", "abc123", registry="gcr.io/p") == "gcr.io/p/unionml-tpu:my-model-abc123"
    assert image_fqn("m", "v1", registry="r", image_name="custom") == "r/custom:m-v1"
    assert image_fqn("m", "v1") == "unionml-tpu:m-v1"


def test_registry_deploy_builds_and_pushes_from_bundle(docker_env, docker_app, tmp_path):
    model = docker_app.model
    model.remote(backend_store=str(tmp_path / "store"), registry="gcr.io/proj")
    version = model.remote_deploy(app_version="img-v1")

    builds, pushes = docker_env("build"), docker_env("push")
    assert len(builds) == 1 and len(pushes) == 1
    fqn = "gcr.io/proj/unionml-tpu:remote-model-img-v1"
    assert fqn in builds[0] and fqn in pushes[0]
    # build context is the deployed BUNDLE, not the working tree
    bundle = tmp_path / "store" / "unionml-tpu" / "development" / "apps" / "remote_model" / version / "bundle"
    assert builds[0].split()[1] == str(bundle)
    # the app shipped no Dockerfile: the default TPU-VM one was generated into the bundle
    assert (bundle / "Dockerfile").exists()
    assert "jax[tpu]" in (bundle / "Dockerfile").read_text()
    manifest = json.loads((bundle.parent / "manifest.json").read_text())
    assert manifest["image"] == fqn


def test_patch_deploy_skips_image_work(docker_env, docker_app, tmp_path):
    """Reference parity: patch (fast) registration re-ships source only
    (model.py:700-701) — no build, no push."""
    model = docker_app.model
    model.remote(backend_store=str(tmp_path / "store"), registry="gcr.io/proj")
    model.remote_deploy(app_version="img-v2")
    assert len(docker_env("build")) == 1

    model.remote_deploy(app_version="img-v2b", patch=True)
    assert len(docker_env("build")) == 1  # unchanged
    assert len(docker_env("push")) == 1


def test_no_registry_means_no_image(docker_env, docker_app, tmp_path):
    model = docker_app.model
    model.remote(backend_store=str(tmp_path / "store"))
    version = model.remote_deploy(app_version="img-v3")
    assert docker_env() == []
    store = tmp_path / "store" / "unionml-tpu" / "development"
    manifest = json.loads((store / "apps" / "remote_model" / version / "manifest.json").read_text())
    assert manifest["image"] is None


def test_build_failure_fails_deploy_before_registration(docker_env, docker_app, tmp_path, monkeypatch):
    monkeypatch.setenv("DOCKER_FAIL_BUILD", "1")
    model = docker_app.model
    model.remote(backend_store=str(tmp_path / "store"), registry="gcr.io/proj")
    with pytest.raises(RuntimeError, match="docker build"):
        model.remote_deploy(app_version="img-v4")
    # the app version is NOT registered: no manifest, so latest_app_version skips it
    manifest = tmp_path / "store" / "unionml-tpu" / "development" / "apps" / "remote_model" / "img-v4" / "manifest.json"
    assert not manifest.exists()
    assert docker_env("push") == []


def test_app_dockerfile_is_respected(docker_env, docker_app, tmp_path, monkeypatch):
    (tmp_path / "appsrc" / "Dockerfile").write_text("FROM scratch\n# custom\n")
    # commit state doesn't matter: explicit app_version skips the git probe
    model = docker_app.model
    model.remote(backend_store=str(tmp_path / "store"), registry="r")
    version = model.remote_deploy(app_version="img-v5")
    bundle = tmp_path / "store" / "unionml-tpu" / "development" / "apps" / "remote_model" / version / "bundle"
    assert (bundle / "Dockerfile").read_text() == "FROM scratch\n# custom\n"


def test_container_launcher_trains_end_to_end(docker_env, docker_app, tmp_path):
    """The image IS the execution vehicle (reference remote.py:91-108 parity):
    deploy builds+pushes the app image, remote_train launches it through
    ContainerLauncher, and the shim executes the container's job_runner
    entrypoint locally — the artifact comes back through the mounted store."""
    from unionml_tpu.launcher import ContainerLauncher

    model = docker_app.model
    model.remote(
        backend_store=str(tmp_path / "store"), registry="gcr.io/proj", launcher=ContainerLauncher()
    )
    model.remote_deploy(app_version="run-v1")
    artifact = model.remote_train(hyperparameters={"max_iter": 200}, wait=True)
    assert artifact.metrics["train"] > 0.8

    runs = docker_env("run")
    assert len(runs) == 1
    line = runs[0]
    assert "gcr.io/proj/unionml-tpu:remote-model-run-v1" in line  # manifest image
    store = str((tmp_path / "store").resolve())
    assert f"-v {store}" in line and "--network host" in line  # store mount + host net
    assert "--rm" in line and "-e PYTHONPATH=" in line


def test_container_launcher_without_image_is_a_clear_error(docker_app, tmp_path):
    """No registry at deploy -> no image in the manifest -> ContainerLauncher
    refuses with guidance instead of launching a broken docker command."""
    from unionml_tpu.launcher import ContainerLauncher

    model = docker_app.model
    model.remote(backend_store=str(tmp_path / "store"), launcher=ContainerLauncher())
    model.remote_deploy(app_version="run-v2")
    with pytest.raises(Exception, match="registry|image"):
        model.remote_train(hyperparameters={"max_iter": 200}, wait=True)


def test_container_run_failure_consumes_retry(docker_env, docker_app, tmp_path, monkeypatch):
    """A dead container (docker run exit 125) is a dead worker to the watchdog:
    with retries=1 the execution resubmits and completes — two run invocations."""
    from unionml_tpu.launcher import ContainerLauncher

    monkeypatch.setenv("DOCKER_FAIL_RUN_ONCE", "1")
    model = docker_app.model
    model.remote(
        backend_store=str(tmp_path / "store"), registry="gcr.io/proj", launcher=ContainerLauncher()
    )
    model.remote_deploy(app_version="run-v3")
    artifact = model.remote_train(hyperparameters={"max_iter": 200}, wait=True, retries=1)
    assert artifact.metrics["train"] > 0.8
    runs = docker_env("run")
    assert len(runs) == 2
    # each attempt mints a fresh container name: a killed attempt's container
    # lingers daemon-side, and reusing the name would fail the retry
    names = [tok for line in runs for i, tok in enumerate(line.split()) if line.split()[i - 1] == "--name"]
    assert len(set(names)) == 2 and names[0].endswith("-a0-w0") and names[1].endswith("-a1-w0")


def test_container_handle_kill_targets_container_and_logs_failure(docker_env, tmp_path, monkeypatch):
    """The watchdog's kill() must reach the CONTAINER (docker kill <name>), not
    just the local client — and a failed docker kill must be loud, because the
    daemon-side worker may still be mutating the mounted store."""
    import logging
    import subprocess as sp

    from unionml_tpu.launcher import _ContainerHandle

    proc = sp.Popen(["sleep", "30"])
    proc2 = None
    handle = _ContainerHandle(proc, "unionml-test-a0-w0")
    try:
        handle.kill()
        proc.wait(timeout=10)
        assert [ln.split()[1] for ln in docker_env("kill")] == ["unionml-test-a0-w0"]

        # a failing docker kill logs the hazard instead of passing silently
        proc2 = sp.Popen(["sleep", "30"])
        handle2 = _ContainerHandle(proc2, "unionml-test-a0-w1")
        monkeypatch.setenv("DOCKER_FAIL_KILL", "1")
        # the package logger does not propagate; capture via a direct handler
        records = []

        class _Catch(logging.Handler):
            def emit(self, record):
                records.append(record)

        from unionml_tpu._logging import logger as pkg_logger

        catcher = _Catch(level=logging.WARNING)
        pkg_logger.addHandler(catcher)
        try:
            handle2.kill()
        finally:
            pkg_logger.removeHandler(catcher)
        proc2.wait(timeout=10)
        assert any("docker kill unionml-test-a0-w1 failed" in r.getMessage() for r in records)
    finally:
        for p in (proc, proc2):
            if p is not None and p.poll() is None:
                p.kill()
