"""Test harness config: run the JAX runtime on an emulated 8-device CPU mesh.

Mirrors the reference's ring structure (SURVEY.md §4): the real runtime executes
in-process (as flytekit-local does there), and multi-chip behavior is exercised without
hardware via XLA's host-platform device emulation — the analog of the reference's
docker Flyte sandbox. An opt-in real-TPU lane is keyed on UNIONML_TPU_CI.
"""

import os
import sys

if not os.environ.get("UNIONML_TPU_CI"):
    # hard-set: the ambient environment pins JAX_PLATFORMS to the real TPU tunnel (axon),
    # and that plugin wins over the env var — the config update below is what sticks.
    os.environ["JAX_PLATFORMS"] = "cpu"
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()

    import jax

    jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# ---------------------------------------------------------------------------
# Shared app fixtures (visible to every ring): mirrors the reference fixture
# architecture (tests/unit/{dataset_fixtures,model_fixtures}.py) — a synthetic
# DataFrame, an sklearn LogisticRegression trainer/predictor/evaluator, and no
# mocking of the execution substrate.

import subprocess
import textwrap
from typing import List

import numpy as np
import pandas as pd
import pytest

from unionml_tpu import Dataset, Model

N_SAMPLES = 100
TEST_SIZE = 0.2


@pytest.fixture
def simple_dataset() -> Dataset:
    dataset = Dataset(name="test_dataset", targets=["y"], test_size=TEST_SIZE)

    @dataset.reader
    def reader(sample_frac: float = 1.0, random_state: int = 42) -> pd.DataFrame:
        rng = np.random.default_rng(17)
        frame = pd.DataFrame({"x1": rng.normal(size=N_SAMPLES), "x2": rng.normal(size=N_SAMPLES)})
        frame["y"] = (frame["x1"] + frame["x2"] > 0).astype(int)
        return frame.sample(frac=sample_frac, random_state=random_state)

    return dataset


@pytest.fixture
def sklearn_model(simple_dataset: Dataset) -> Model:
    from sklearn.linear_model import LogisticRegression

    model = Model(name="test_model", init=LogisticRegression, dataset=simple_dataset)

    @model.trainer
    def trainer(estimator: LogisticRegression, features: pd.DataFrame, target: pd.DataFrame) -> LogisticRegression:
        return estimator.fit(features, target.squeeze())

    @model.predictor
    def predictor(estimator: LogisticRegression, features: pd.DataFrame) -> List[float]:
        return [float(x) for x in estimator.predict(features)]

    @model.evaluator
    def evaluator(estimator: LogisticRegression, features: pd.DataFrame, target: pd.DataFrame) -> float:
        return float(estimator.score(features, target.squeeze()))

    return model


#: the CLI/serving project app used by the CLI round-trip (unit) and the live
#: multiprocess-server test (integration)
CLI_APP_SOURCE = textwrap.dedent(
    """
    from typing import List

    import pandas as pd
    from sklearn.linear_model import LogisticRegression

    from unionml_tpu import Dataset, Model

    dataset = Dataset(name="ds", test_size=0.2, shuffle=True, targets=["y"])
    model = Model(name="cli_test_model", init=LogisticRegression, dataset=dataset)
    model.__app_module__ = "cli_app:model"


    @dataset.reader
    def reader(n: int = 60) -> pd.DataFrame:
        rows = []
        for i in range(n):
            rows.append({"x0": float(i % 7), "x1": float((i * 3) % 5), "y": i % 2})
        return pd.DataFrame(rows)


    @model.trainer
    def trainer(est: LogisticRegression, features: pd.DataFrame, target: pd.DataFrame) -> LogisticRegression:
        return est.fit(features, target.squeeze())


    @model.predictor
    def predictor(est: LogisticRegression, features: pd.DataFrame) -> List[float]:
        return [float(v) for v in est.predict(features)]
    """
)

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture()
def cli_project(tmp_path, monkeypatch):
    """A committed git project containing a unionml-tpu app + an isolated backend store."""
    (tmp_path / "cli_app.py").write_text(CLI_APP_SOURCE)
    subprocess.run(["git", "init", "-q"], cwd=tmp_path, check=True)
    subprocess.run(["git", "add", "."], cwd=tmp_path, check=True)
    subprocess.run(
        ["git", "-c", "user.email=t@t", "-c", "user.name=t", "commit", "-q", "-m", "init"],
        cwd=tmp_path,
        check=True,
    )
    monkeypatch.setenv("UNIONML_TPU_STORE", str(tmp_path / "store"))
    monkeypatch.setenv("PYTHONPATH", os.pathsep.join([str(tmp_path), _REPO_ROOT]))
    monkeypatch.chdir(tmp_path)
    monkeypatch.syspath_prepend(str(tmp_path))
    yield tmp_path
    sys.modules.pop("cli_app", None)
