"""Test harness config: run the JAX runtime on an emulated 8-device CPU mesh.

Mirrors the reference's ring structure (SURVEY.md §4): the real runtime executes
in-process (as flytekit-local does there), and multi-chip behavior is exercised without
hardware via XLA's host-platform device emulation — the analog of the reference's
docker Flyte sandbox. An opt-in real-TPU lane is keyed on UNIONML_TPU_CI.
"""

import os
import sys

if not os.environ.get("UNIONML_TPU_CI"):
    # hard-set: the ambient environment pins JAX_PLATFORMS to the real TPU tunnel (axon),
    # and that plugin wins over the env var — the config update below is what sticks.
    os.environ["JAX_PLATFORMS"] = "cpu"
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()

    import jax

    jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
