"""Radix prefix cache token-identity on the emulated 8-device mesh.

Oracle: serving a prefix from cached paged blocks must be invisible in the
tokens — every warm (cache-hit) stream from a tp=2 engine equals the cold
(first-visit) stream AND a sequential single-device ``Generator`` run (greedy,
f32), including the chunked-admission and paged preempt-resume legs. The
dp=2 x tp=2 ``ReplicaSet`` leg additionally pins that the cached-length
routing probe steers shared-prefix traffic to the replica that holds the
cache while staying exact.
"""

import threading

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from unionml_tpu.models import GenerationConfig, Generator, Llama, LlamaConfig, llama_partition_rules
from unionml_tpu.parallel import MeshSpec
from unionml_tpu.serving import ContinuousBatcher, ReplicaSet

pytestmark = pytest.mark.skipif(len(jax.devices()) < 8, reason="needs 8 emulated devices")

SYSTEM = [7, 7, 3, 9, 1, 2, 5, 11, 4, 8, 6, 10, 12, 3, 2, 9, 5, 1]  # 18 shared tokens
PROMPTS = [SYSTEM + tail for tail in ([30, 31], [30, 32, 33], [40], [30, 31, 35, 36])]


@pytest.fixture(scope="module")
def tiny():
    config = LlamaConfig.tiny(
        vocab_size=96, dim=64, n_layers=2, n_heads=4, n_kv_heads=2, hidden_dim=128,
        dtype=jnp.float32, param_dtype=jnp.float32,
    )
    module = Llama(config)
    params = module.init(jax.random.PRNGKey(1), jnp.zeros((1, 8), jnp.int32))["params"]
    return module, params


def _cfg(**overrides):
    base = dict(max_new_tokens=8, temperature=0.0, prompt_buckets=(32,))
    base.update(overrides)
    return GenerationConfig(**base)


def _expected(module, params, prompts, cfg=None):
    gen = Generator(module, params, cfg or _cfg())
    return [list(gen([p])[0]) for p in prompts]


def _drain(stream):
    return [int(t) for chunk in stream for t in np.asarray(chunk).ravel()]


def _drain_concurrently(streams):
    results = [None] * len(streams)

    def worker(i):
        results[i] = _drain(streams[i])

    threads = [threading.Thread(target=worker, args=(i,)) for i in range(len(streams))]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=180)
    return results


def test_tp2_cached_prefix_equals_cold_and_sequential(tiny):
    """tp=2 leg: the heads-major pools shard over the model axis, the block
    gather/scatter ride the same sharding, and warm streams — chunked
    admission starting mid-prompt at the first uncached token — equal the
    cold first-visit stream and the single-device sequential run exactly."""
    module, params = tiny
    expected = _expected(module, params, PROMPTS)
    mesh = MeshSpec(data=1, model=2).build(devices=jax.devices()[:2])
    gen = Generator(module, params, _cfg(), mesh=mesh, partition_rules=llama_partition_rules())
    batcher = ContinuousBatcher(
        gen, slots=2, decode_chunk=4, block_size=8, admit_chunk=8, prefix_cache=True
    )
    try:
        cold = _drain(batcher.submit(PROMPTS[0]))  # publishes SYSTEM's blocks
        assert cold == expected[0]
        warm = [_drain(batcher.submit(p)) for p in PROMPTS[1:]]
        assert warm == expected[1:]
        stats = batcher.stats()["prefix_cache"]
        assert stats["hits"] == len(PROMPTS) - 1
        assert stats["tokens_avoided"] > 0
    finally:
        batcher.close()


def test_tp2_chunked_and_preempt_resume_legs_stay_exact(tiny):
    """The two hard admission legs under the cache, on the TP mesh: chunked
    interleaving (max_admissions > 1) and pool-pressure preempt-resume — the
    resume's prompt + echo re-matches its own published blocks and the
    streams stay token-identical throughout."""
    module, params = tiny
    cfg = _cfg(max_new_tokens=16, prompt_buckets=(16,))
    long_prompts = [[3, 1, 4, 1, 5, 9, 2, 6, 5, 3, 5, 8, 9, 7], [2, 7, 1, 8, 2, 8, 1, 8, 2, 8, 4, 5, 9, 4]]
    expected = _expected(module, params, long_prompts, cfg)
    mesh = MeshSpec(data=1, model=2).build(devices=jax.devices()[:2])
    gen = Generator(module, params, cfg, mesh=mesh, partition_rules=llama_partition_rules())
    probe = ContinuousBatcher(gen, slots=2, decode_chunk=8, block_size=8,
                              admit_chunk=8, prefix_cache=True)
    pool = 2 * probe._blocks_initial(long_prompts[0], cfg.max_new_tokens)
    probe.close()
    batcher = ContinuousBatcher(
        gen, slots=2, decode_chunk=8, block_size=8, pool_blocks=pool,
        admit_chunk=8, max_admissions=2, prefix_cache=True,
    )
    try:
        streams = [batcher.submit(p) for p in long_prompts]
        assert _drain_concurrently(streams) == expected
        assert batcher.stats()["kv_blocks"]["preemptions"] > 0
    finally:
        batcher.close()


def test_dp2_tp2_replicaset_routes_on_actual_cached_length(tiny):
    """dp=2 x tp=2 leg: the delegation path carries prefix_cache to every
    replica, warm shared-prefix prompts are steered to the replica whose
    radix tree actually holds the prefix (not an LRU guess), and the fleet's
    streams equal the sequential single-device run."""
    module, params = tiny
    expected = _expected(module, params, PROMPTS)
    mesh = MeshSpec(data=2, model=2).build(devices=jax.devices()[:4])
    gen = Generator(module, params, _cfg(), mesh=mesh, partition_rules=llama_partition_rules())
    engine = ContinuousBatcher(
        gen, slots=2, decode_chunk=4, block_size=8, admit_chunk=8, prefix_cache=True
    )
    try:
        assert isinstance(engine, ReplicaSet) and engine.replicas == 2
        for batcher in engine.batchers:
            assert batcher._radix is not None
        results = [_drain(engine.submit(p)) for p in PROMPTS]
        assert results == expected
        stats = engine.stats()
        assert stats["prefix_cache"]["hits"] >= len(PROMPTS) - 1
        # every request after the first followed the cache to one replica
        assert max(stats["scheduler"]["submitted"]) >= len(PROMPTS) - 1
        assert stats["scheduler"]["affinity_hits"] >= len(PROMPTS) - 1
        per_replica_hits = [
            (entry.get("prefix_cache") or {}).get("hits", 0) for entry in stats["per_replica"]
        ]
        assert sum(per_replica_hits) == stats["prefix_cache"]["hits"]
    finally:
        engine.close()
