"""Model-library tests on the emulated 8-device mesh: forward shapes, TP/FSDP/SP
training steps, LoRA masking, attention-kernel parity."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
import optax
from flax.training import train_state

from unionml_tpu import MeshSpec, TrainerConfig, make_train_step
from unionml_tpu.models import (
    BertConfig,
    BertEncoder,
    Llama,
    LlamaConfig,
    MLPClassifier,
    MLPConfig,
    ViT,
    ViTConfig,
    bert_partition_rules,
    causal_lm_loss,
    classification_loss,
    llama_partition_rules,
    lora_optimizer,
    lora_param_labels,
)
from unionml_tpu.ops.attention import dot_product_attention
from unionml_tpu.ops.flash_attention import flash_attention
from unionml_tpu.ops.ring_attention import sequence_sharded_attention
from unionml_tpu.train import fit

RNG = jax.random.PRNGKey(0)


def _tokens(batch=8, length=64, vocab=512):
    return jax.random.randint(RNG, (batch, length), 0, vocab)


# ---------------------------------------------------------------- attention kernels


def test_flash_attention_matches_reference():
    q, k, v = (jax.random.normal(jax.random.PRNGKey(i), (2, 256, 4, 128)) for i in range(3))
    for causal in (False, True):
        ref = dot_product_attention(q, k, v, causal=causal)
        out = flash_attention(q, k, v, causal=causal, interpret=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_flash_attention_gradients_match():
    q, k, v = (jax.random.normal(jax.random.PRNGKey(i), (1, 128, 2, 128)) for i in range(3))
    g = jax.grad(lambda *a: flash_attention(*a, causal=True, interpret=True).sum(), argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(lambda *a: dot_product_attention(*a, causal=True).sum(), argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=2e-5)


def test_flash_attention_custom_blocks_gradients_match():
    """blocks= threads through the BACKWARD too: q_len=192 tiles under (64, 64)
    but not under the defaults, so a backward that ignored the override would
    either leave tail rows unwritten (round-3 behavior) or now raise — the
    gradients must match the XLA reference across the full length."""
    q, k, v = (jax.random.normal(jax.random.PRNGKey(i), (1, 192, 2, 128)) for i in range(3))
    g = jax.grad(
        lambda *a: flash_attention(*a, causal=True, interpret=True, blocks=(64, 64)).sum(),
        argnums=(0, 1, 2),
    )(q, k, v)
    g_ref = jax.grad(lambda *a: dot_product_attention(*a, causal=True).sum(), argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=2e-5)


def test_flash_attention_gqa_gradients_group_sum():
    """The fused backward computes dk/dv at query-head resolution then group-sums
    for GQA (repeat's transpose); gradients must match the head-repeating XLA
    reference exactly, including shapes [B, Lk, Hkv, D]."""
    q = jax.random.normal(jax.random.PRNGKey(0), (1, 128, 4, 128))
    k = jax.random.normal(jax.random.PRNGKey(1), (1, 128, 2, 128))
    v = jax.random.normal(jax.random.PRNGKey(2), (1, 128, 2, 128))
    g_flash = jax.grad(
        lambda *a: (flash_attention(*a, causal=True, interpret=True) ** 2).sum(), argnums=(0, 1, 2)
    )(q, k, v)
    g_ref = jax.grad(
        lambda *a: (dot_product_attention(*a, causal=True) ** 2).sum(), argnums=(0, 1, 2)
    )(q, k, v)
    assert g_flash[1].shape == k.shape and g_flash[2].shape == v.shape
    for a, b in zip(g_flash, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=3e-4)


def test_attention_empty_causal_rows_are_zero_everywhere():
    """q_len > k_len causal: rows attending NO keys are zero — a convention all
    three implementations (dense reference, flash, fused backward) must share;
    softmax over an all-masked row must never leak a uniform mean of V."""
    q = jax.random.normal(jax.random.PRNGKey(0), (1, 256, 1, 128))
    k = jax.random.normal(jax.random.PRNGKey(1), (1, 128, 1, 128))
    v = jax.random.normal(jax.random.PRNGKey(2), (1, 128, 1, 128))
    ref = dot_product_attention(q, k, v, causal=True)
    np.testing.assert_array_equal(np.asarray(ref[:, :128]), 0.0)  # offset=-128: first 128 rows empty
    out = flash_attention(q, k, v, causal=True, interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)
    g_flash = jax.grad(
        lambda *a: (flash_attention(*a, causal=True, interpret=True) ** 2).sum(), argnums=(0, 1, 2)
    )(q, k, v)
    g_ref = jax.grad(
        lambda *a: (dot_product_attention(*a, causal=True) ** 2).sum(), argnums=(0, 1, 2)
    )(q, k, v)
    for a, b in zip(g_flash, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=3e-4)


def test_flash_attention_cross_length_gradients():
    """q_len != k_len backward: the offset-shifted causal diagonal must mask the
    recomputed scores identically in the dq and dkv kernels."""
    q = jax.random.normal(jax.random.PRNGKey(0), (1, 128, 1, 128))
    k = jax.random.normal(jax.random.PRNGKey(1), (1, 256, 1, 128))
    v = jax.random.normal(jax.random.PRNGKey(2), (1, 256, 1, 128))
    g_flash = jax.grad(
        lambda *a: (flash_attention(*a, causal=True, interpret=True) ** 2).sum(), argnums=(0, 1, 2)
    )(q, k, v)
    g_ref = jax.grad(
        lambda *a: (dot_product_attention(*a, causal=True) ** 2).sum(), argnums=(0, 1, 2)
    )(q, k, v)
    for a, b in zip(g_flash, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=3e-4)


def test_flash_attention_causal_cross_lengths():
    """q_len != k_len: causal masking must use the shifted diagonal (query i attends
    keys up to i + k_len - q_len), matching the XLA reference."""
    q = jax.random.normal(jax.random.PRNGKey(0), (1, 128, 2, 128))
    k = jax.random.normal(jax.random.PRNGKey(1), (1, 256, 2, 128))
    v = jax.random.normal(jax.random.PRNGKey(2), (1, 256, 2, 128))
    ref = dot_product_attention(q, k, v, causal=True)
    out = flash_attention(q, k, v, causal=True, interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_flash_attention_grouped_query_native():
    """The kernel consumes grouped-query KV unexpanded: its index maps route query
    head h to KV head h * n_kv // n_heads, so repeated heads are never
    materialized. Numerics must match the (head-repeating) XLA reference."""
    q = jax.random.normal(jax.random.PRNGKey(0), (1, 256, 8, 128))
    k = jax.random.normal(jax.random.PRNGKey(1), (1, 256, 2, 128))
    v = jax.random.normal(jax.random.PRNGKey(2), (1, 256, 2, 128))
    ref = dot_product_attention(q, k, v, causal=True)
    out = flash_attention(q, k, v, causal=True, interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_flash_attention_rejects_indivisible_heads():
    q = jax.random.normal(jax.random.PRNGKey(0), (1, 128, 6, 128))
    k = jax.random.normal(jax.random.PRNGKey(1), (1, 128, 4, 128))
    with pytest.raises(ValueError, match="multiple of KV heads"):
        flash_attention(q, k, k, interpret=True)


def test_ring_attention_matches_reference():
    q, k, v = (jax.random.normal(jax.random.PRNGKey(i), (2, 256, 4, 64)) for i in range(3))
    mesh = MeshSpec(data=2, sequence=4).build()
    for causal in (False, True):
        ref = dot_product_attention(q, k, v, causal=causal)
        out = sequence_sharded_attention(q, k, v, mesh, causal=causal)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_ulysses_attention_matches_reference():
    """All-to-all sequence parallelism: same numerics as dense attention."""
    q, k, v = (jax.random.normal(jax.random.PRNGKey(i), (2, 256, 4, 64)) for i in range(3))
    mesh = MeshSpec(data=2, sequence=4).build()
    for causal in (False, True):
        ref = dot_product_attention(q, k, v, causal=causal)
        out = sequence_sharded_attention(q, k, v, mesh, causal=causal, impl="ulysses")
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_ulysses_attention_grouped_query():
    q = jax.random.normal(jax.random.PRNGKey(0), (2, 128, 8, 32))
    k = jax.random.normal(jax.random.PRNGKey(1), (2, 128, 2, 32))
    v = jax.random.normal(jax.random.PRNGKey(2), (2, 128, 2, 32))
    mesh = MeshSpec(data=1, sequence=8).build()
    ref = dot_product_attention(q, k, v, causal=True)
    out = sequence_sharded_attention(q, k, v, mesh, causal=True, batch_axes=(), impl="ulysses")
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_ring_attention_gradients_match_reference():
    """Training runs through ring attention's autodiff (ppermute transposes to the
    reverse rotation); gradients w.r.t. q/k/v must match dense attention."""
    q, k, v = (jax.random.normal(jax.random.PRNGKey(i), (1, 128, 2, 32)) for i in range(3))
    mesh = MeshSpec(data=1, sequence=8).build()

    def ring_loss(q, k, v):
        return (sequence_sharded_attention(q, k, v, mesh, causal=True, batch_axes=()) ** 2).mean()

    def dense_loss(q, k, v):
        return (dot_product_attention(q, k, v, causal=True) ** 2).mean()

    g_ring = jax.jit(jax.grad(ring_loss, argnums=(0, 1, 2)))(q, k, v)
    g_dense = jax.jit(jax.grad(dense_loss, argnums=(0, 1, 2)))(q, k, v)
    for a, b in zip(g_ring, g_dense):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=2e-5)


def test_ulysses_attention_gradients_match_reference():
    q, k, v = (jax.random.normal(jax.random.PRNGKey(i), (2, 128, 4, 32)) for i in range(3))
    mesh = MeshSpec(data=2, sequence=4).build()

    def ulysses_loss(q, k, v):
        out = sequence_sharded_attention(q, k, v, mesh, causal=True, impl="ulysses")
        return (out**2).mean()

    def dense_loss(q, k, v):
        return (dot_product_attention(q, k, v, causal=True) ** 2).mean()

    g_u = jax.jit(jax.grad(ulysses_loss, argnums=(0, 1, 2)))(q, k, v)
    g_d = jax.jit(jax.grad(dense_loss, argnums=(0, 1, 2)))(q, k, v)
    for a, b in zip(g_u, g_d):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=2e-5)


def test_ring_attention_grouped_query():
    q = jax.random.normal(jax.random.PRNGKey(0), (2, 128, 8, 32))
    k = jax.random.normal(jax.random.PRNGKey(1), (2, 128, 2, 32))
    v = jax.random.normal(jax.random.PRNGKey(2), (2, 128, 2, 32))
    mesh = MeshSpec(data=1, sequence=8).build()
    ref = dot_product_attention(q, k, v, causal=True)
    out = sequence_sharded_attention(q, k, v, mesh, causal=True, batch_axes=())
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


# ---------------------------------------------------------------- llama


@pytest.fixture(scope="module")
def tiny_llama():
    cfg = LlamaConfig.tiny(dtype=jnp.float32)
    module = Llama(cfg)
    params = module.init(RNG, _tokens(2, 16, cfg.vocab_size))["params"]
    return cfg, module, params


def test_llama_forward_shape(tiny_llama):
    cfg, module, params = tiny_llama
    logits = module.apply({"params": params}, _tokens(2, 16, cfg.vocab_size))
    assert logits.shape == (2, 16, cfg.vocab_size)


def test_llama_train_step_tp_fsdp_mesh(tiny_llama):
    cfg, module, params = tiny_llama
    state = train_state.TrainState.create(apply_fn=module.apply, params=params, tx=optax.adam(1e-3))
    loss_fn = lambda p, batch: causal_lm_loss(lambda pp, t: module.apply({"params": pp}, t), p, batch)  # noqa: E731
    step = make_train_step(loss_fn)
    tokens = np.asarray(_tokens(32, 32, cfg.vocab_size))
    result = fit(
        state,
        step,
        tokens,
        TrainerConfig(
            epochs=2,
            batch_size=16,
            mesh=MeshSpec(data=2, fsdp=2, model=2),
            partition_rules=llama_partition_rules(),
            fsdp_min_weight_size=1024,
        ),
    )
    assert result.steps == 4
    assert np.isfinite(result.history[-1]["loss"])
    # TP rule actually applied: q_proj kernel carries the model axis
    spec = str(result.state.params["layer_0"]["attn"]["q_proj"]["kernel"].sharding.spec)
    assert "model" in spec


def test_llama_lora_freezes_base_params():
    cfg = LlamaConfig.tiny(lora_rank=4, dtype=jnp.float32)
    module = Llama(cfg)
    tokens = _tokens(4, 16, cfg.vocab_size)
    params = module.init(RNG, tokens)["params"]
    labels = lora_param_labels(params)
    assert labels["layer_0"]["attn"]["q_proj"]["lora_a"] == "lora"
    assert labels["layer_0"]["attn"]["q_proj"]["kernel"] == "frozen"

    state = train_state.TrainState.create(apply_fn=module.apply, params=params, tx=lora_optimizer(1e-3))
    loss_fn = lambda p, b: causal_lm_loss(lambda pp, t: module.apply({"params": pp}, t), p, b)  # noqa: E731
    new_state, metrics = jax.jit(make_train_step(loss_fn))(state, np.asarray(tokens))
    base_before = params["layer_0"]["attn"]["q_proj"]["kernel"]
    base_after = new_state.params["layer_0"]["attn"]["q_proj"]["kernel"]
    np.testing.assert_array_equal(np.asarray(base_before), np.asarray(base_after))
    lora_before = params["layer_0"]["attn"]["q_proj"]["lora_a"]
    lora_after = new_state.params["layer_0"]["attn"]["q_proj"]["lora_a"]
    assert not np.array_equal(np.asarray(lora_before), np.asarray(lora_after))


@pytest.mark.parametrize(
    "impl,mesh_axes,batch_entry",
    [
        ("ring", dict(data=1, sequence=8), None),
        # ulysses: the sequence-axis size (4) must divide the head count
        # (4 after GQA expansion), so it runs on a smaller sequence axis
        ("ulysses", dict(data=2, sequence=4), "data"),
    ],
)
def test_llama_sequence_parallel_end_to_end(impl, mesh_axes, batch_entry):
    """Full decoder under shard_map with each sequence-parallel impl matches
    impl='xla': ring (K/V rotation) and ulysses (all-to-all) wired through the
    model library."""
    cfg_sp = LlamaConfig.tiny(attention_impl=impl, dtype=jnp.float32)
    cfg_ref = LlamaConfig.tiny(attention_impl="xla", dtype=jnp.float32)
    tokens = _tokens(2, 64, cfg_ref.vocab_size)
    params = Llama(cfg_ref).init(RNG, tokens)["params"]

    ref = Llama(cfg_ref).apply({"params": params}, tokens)

    from jax import lax, shard_map
    from jax.sharding import PartitionSpec as P

    mesh = MeshSpec(**mesh_axes).build()

    # positions must be the *global* positions of the local shard: pass explicitly
    def fwd(tokens_local, params):
        seq_idx = lax.axis_index("sequence")
        local_len = tokens_local.shape[1]
        positions = seq_idx * local_len + jnp.arange(local_len)
        return Llama(cfg_sp).apply({"params": params}, tokens_local, positions)

    out = shard_map(
        fwd,
        mesh=mesh,
        in_specs=(P(batch_entry, "sequence"), P()),
        out_specs=P(batch_entry, "sequence", None),
        check_vma=False,
    )(tokens, params)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-4)


# ---------------------------------------------------------------- bert / vit / mlp


def test_bert_classification_step():
    cfg = BertConfig.tiny(dtype=jnp.float32)
    module = BertEncoder(cfg)
    tokens = _tokens(8, 32, cfg.vocab_size)
    labels = np.asarray(jax.random.randint(RNG, (8,), 0, cfg.num_classes))
    params = module.init(RNG, tokens)["params"]
    state = train_state.TrainState.create(apply_fn=module.apply, params=params, tx=optax.adam(1e-3))

    loss_fn = lambda p, b: classification_loss(lambda pp, t: module.apply({"params": pp}, t), p, b)  # noqa: E731
    step = make_train_step(loss_fn, has_aux=True)
    result = fit(
        state,
        step,
        [np.asarray(tokens), labels],
        TrainerConfig(epochs=2, batch_size=4, mesh=MeshSpec(data=-1), partition_rules=bert_partition_rules()),
    )
    assert "accuracy" in result.history[-1]


def test_bert_attention_mask_blocks_padding():
    """Pad tokens must not influence the [CLS] representation: changing token ids at
    masked positions leaves the logits unchanged, and masking must change the output
    vs. no mask."""
    cfg = BertConfig.tiny(dtype=jnp.float32)
    module = BertEncoder(cfg)
    tokens = np.asarray(_tokens(2, 16, cfg.vocab_size))
    params = module.init(RNG, jnp.asarray(tokens))["params"]
    mask = np.ones((2, 16), dtype=np.int32)
    mask[:, 8:] = 0  # second half is padding

    logits = module.apply({"params": params}, jnp.asarray(tokens), jnp.asarray(mask))
    tokens_perturbed = tokens.copy()
    tokens_perturbed[:, 8:] = (tokens_perturbed[:, 8:] + 7) % cfg.vocab_size
    logits_perturbed = module.apply({"params": params}, jnp.asarray(tokens_perturbed), jnp.asarray(mask))
    np.testing.assert_allclose(np.asarray(logits), np.asarray(logits_perturbed), atol=1e-6)

    logits_unmasked = module.apply({"params": params}, jnp.asarray(tokens))
    assert not np.allclose(np.asarray(logits), np.asarray(logits_unmasked))

    # the 3-tuple batch shape routes the mask through classification_loss
    labels = np.zeros((2,), dtype=np.int32)
    loss, aux = classification_loss(
        lambda pp, t, m=None: module.apply({"params": pp}, t, m), params, (tokens, mask, labels)
    )
    assert np.isfinite(float(loss)) and "accuracy" in aux


def test_bert_aux_metrics_survive_grad_accum():
    cfg = BertConfig.tiny(dtype=jnp.float32)
    module = BertEncoder(cfg)
    tokens = _tokens(8, 16, cfg.vocab_size)
    labels = np.asarray(jax.random.randint(RNG, (8,), 0, cfg.num_classes))
    params = module.init(RNG, tokens)["params"]
    state = train_state.TrainState.create(apply_fn=module.apply, params=params, tx=optax.adam(1e-3))
    loss_fn = lambda p, b: classification_loss(lambda pp, t: module.apply({"params": pp}, t), p, b)  # noqa: E731
    step = make_train_step(loss_fn, has_aux=True, grad_accum_steps=2)
    _, metrics = jax.jit(step)(state, (np.asarray(tokens), labels))
    assert "accuracy" in metrics


def test_vit_forward_and_step():
    cfg = ViTConfig.tiny(dtype=jnp.float32)
    module = ViT(cfg)
    images = jax.random.normal(RNG, (4, cfg.image_size, cfg.image_size, 3))
    params = module.init(RNG, images)["params"]
    logits = module.apply({"params": params}, images)
    assert logits.shape == (4, cfg.num_classes)


def test_mlp_classifier():
    module = MLPClassifier(MLPConfig(features=(32,), num_classes=3, dtype=jnp.float32))
    x = jax.random.normal(RNG, (5, 16))
    params = module.init(RNG, x)["params"]
    assert module.apply({"params": params}, x).shape == (5, 3)


def test_chunked_causal_lm_loss_matches_plain():
    """chunked_causal_lm_loss (scan over vocab-chunks, remat body) must equal
    causal_lm_loss exactly — loss, gradients, and the masked variant."""
    from unionml_tpu.models import Llama, LlamaConfig, causal_lm_loss, chunked_causal_lm_loss

    cfg = LlamaConfig.tiny(
        dim=64, n_layers=2, n_heads=4, n_kv_heads=2, hidden_dim=128, vocab_size=97,
        dtype=jnp.float32, param_dtype=jnp.float32,
    )
    module = Llama(cfg)
    tokens = jax.random.randint(jax.random.PRNGKey(0), (3, 33), 0, 97)  # 32 targets: pads to 2x16
    params = module.init(jax.random.PRNGKey(1), tokens)["params"]

    plain = causal_lm_loss(lambda p, t: module.apply({"params": p}, t), params, tokens)
    chunked = chunked_causal_lm_loss(module, params, tokens, chunk_size=16)
    np.testing.assert_allclose(float(plain), float(chunked), rtol=1e-5)

    g_plain = jax.grad(lambda p: causal_lm_loss(lambda pp, t: module.apply({"params": pp}, t), p, tokens))(params)
    g_chunked = jax.grad(lambda p: chunked_causal_lm_loss(module, p, tokens, chunk_size=16))(params)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-5), g_plain, g_chunked
    )

    mask = (tokens > 10).astype(jnp.int32)
    plain_m = causal_lm_loss(lambda p, t: module.apply({"params": p}, t), params, (tokens, mask))
    chunked_m = chunked_causal_lm_loss(module, params, (tokens, mask), chunk_size=16)
    np.testing.assert_allclose(float(plain_m), float(chunked_m), rtol=1e-5)
