"""Compiled predictor over a device mesh: multi-chip serving without hardware.

The serving story's multi-chip half (ServingConfig.mesh): padded batches are
placed sharded over the data axis, params replicated, and the per-bucket jit
cache holds across request sizes — validated on the emulated 8-device mesh.
"""

import asyncio
import json
from typing import Any, Dict

import numpy as np
import pandas as pd
import pytest

import jax
import jax.numpy as jnp

from unionml_tpu import Dataset, Model, MeshSpec
from unionml_tpu.serving import ServingConfig

pytestmark = pytest.mark.skipif(len(jax.devices()) < 8, reason="needs 8 emulated devices")

FEATURES = 8


def _mesh_serving_model():
    dataset = Dataset(name="mesh_serving_ds", targets=["y"], test_size=0.2)

    @dataset.reader
    def reader(n: int = 64) -> pd.DataFrame:
        rng = np.random.default_rng(0)
        frame = pd.DataFrame(
            rng.normal(size=(n, FEATURES)).astype("float32"),
            columns=[f"f{i}" for i in range(FEATURES)],
        )
        frame["y"] = (frame.sum(axis=1) > 0).astype("int32")
        return frame

    def init(hyperparameters: Any = None) -> Dict[str, Any]:
        rng = np.random.default_rng(1)
        return {"w": rng.normal(size=(FEATURES, 2)).astype("float32")}

    model = Model(name="mesh_serving_model", init=init, dataset=dataset)

    @model.trainer
    def trainer(params: Dict[str, Any], features: pd.DataFrame, target: pd.DataFrame) -> Dict[str, Any]:
        return params

    @model.predictor(
        config=ServingConfig(
            max_batch_size=32,
            max_wait_ms=1.0,
            bucket_sizes=[8, 32],
            feature_shape=(FEATURES,),
            mesh=MeshSpec(data=4, model=2),
        )
    )
    def predictor(params: Dict[str, Any], features: Any) -> list:
        return jnp.argmax(features @ params["w"], axis=-1)

    @model.evaluator
    def evaluator(params: Dict[str, Any], features: pd.DataFrame, target: pd.DataFrame) -> float:
        return 0.0

    return model


def test_mesh_placed_predictor_end_to_end():
    model = _mesh_serving_model()
    model.train()
    app = model.serve()

    compiled = model._compiled_predictor
    # buckets rounded up to multiples of the data axis (4): 8 and 32 already are
    assert compiled._buckets() == (8, 32)

    rng = np.random.default_rng(2)
    for n in (1, 3, 8, 11, 32, 5):
        records = [
            {f"f{i}": float(v) for i, v in enumerate(rng.normal(size=FEATURES))} for _ in range(n)
        ]
        status, preds, _ = asyncio.run(
            app.dispatch("POST", "/predict", json.dumps({"features": records}).encode())
        )
        assert status == 200 and len(preds) == n
        # oracle: eager numpy compute
        X = np.array([[r[f"f{i}"] for i in range(FEATURES)] for r in records], dtype=np.float32)
        expected = (X @ model.artifact.model_object["w"]).argmax(-1).tolist()
        assert preds == expected

    assert not compiled._eager
    assert compiled.traces == 2  # one compile per bucket across all request sizes
    # the placed params really live replicated on the mesh
    placed = compiled._placed_params
    assert placed is not None
    assert len(placed["w"].sharding.device_set) == 8


def test_continuous_batching_over_tp_mesh():
    """Continuous batching over a tensor-parallel mesh: params and KV heads
    shard over the model axis, admission prefills at batch 1 (replicated), and
    every concurrent stream's tokens equal the UNSHARDED sequential run — the
    sharding must be invisible in the output, exactly as for the plain
    Generator (test_generate_tp.py)."""
    import threading

    from unionml_tpu.models import (
        GenerationConfig,
        Generator,
        Llama,
        LlamaConfig,
        llama_partition_rules,
    )
    from unionml_tpu.serving import ContinuousBatcher

    config = LlamaConfig.tiny(
        vocab_size=96, dim=64, n_layers=2, n_heads=4, n_kv_heads=2, hidden_dim=128,
        dtype=jnp.float32, param_dtype=jnp.float32,
    )
    module = Llama(config)
    params = module.init(jax.random.PRNGKey(1), jnp.zeros((1, 8), jnp.int32))["params"]
    cfg = GenerationConfig(max_new_tokens=8, temperature=0.0, prompt_buckets=(16,))
    prompts = [[3, 1, 4, 1, 5], [9, 2, 6, 5, 3, 5, 8, 9], [7, 1], [6, 6, 6, 2]]

    plain = Generator(module, params, cfg)
    expected = []
    for p in prompts:
        expected.append(list(plain([p])[0]))

    mesh = MeshSpec(data=1, model=2).build(jax.devices()[:2])
    sharded = Generator(module, params, cfg, mesh=mesh, partition_rules=llama_partition_rules())
    batcher = ContinuousBatcher(sharded, slots=2, decode_chunk=3)
    try:
        results = [None] * len(prompts)

        def worker(i):
            results[i] = [
                int(t) for chunk in batcher.submit(prompts[i]) for t in np.asarray(chunk).ravel()
            ]

        threads = [threading.Thread(target=worker, args=(i,)) for i in range(len(prompts))]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=300)
        assert results == expected
        assert batcher.decoded_rows > batcher.decode_dispatches  # dispatches were shared
    finally:
        batcher.close()

    # batch-axis sharding cannot run through ONE engine (batch-1 admissions
    # don't split a batch axis) — construction now delegates to the replica
    # layer instead of rejecting; tests/emulated/test_replicas.py pins its
    # token-exactness. A SUBCLASS built directly still gets the clear error.
    from unionml_tpu.serving import ReplicaSet

    data_mesh = MeshSpec(data=2, model=2).build(jax.devices()[:4])
    data_gen = Generator(module, params, cfg, mesh=data_mesh, partition_rules=llama_partition_rules())
    delegated = ContinuousBatcher(data_gen, slots=2)
    assert isinstance(delegated, ReplicaSet) and delegated.replicas == 2
    delegated.close()

    class _DirectEngine(ContinuousBatcher):
        pass

    with pytest.raises(ValueError, match="model/TP"):
        _DirectEngine(data_gen, slots=2)
