"""End-to-end request tracing on the emulated dp=2 x tp=2 mesh (the ISSUE-5
acceptance shape): a request dispatched through the serving HTTP layer into a
chunked-prefill :class:`ReplicaSet` must leave one ``/debug/requests/<id>``
timeline carrying queue-wait, the routed replica (and the load it saw), every
prefill chunk, and per-emission events — all on one non-decreasing
monotonic-clock axis — and ``/metrics?format=prometheus`` must parse under the
text-format grammar."""

import asyncio
import json
import re
import threading

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from unionml_tpu.models import GenerationConfig, Llama, LlamaConfig, llama_partition_rules
from unionml_tpu.observability import FlightRecorder, Tracer, render_prometheus
from unionml_tpu.parallel import MeshSpec
from unionml_tpu.serving import ReplicaSet
from unionml_tpu.serving.http import HTTPServer
from unionml_tpu.serving.metrics import ServingMetrics

pytestmark = pytest.mark.skipif(len(jax.devices()) < 8, reason="needs 8 emulated devices")

PROMPT_LEN = 14  # pads to the 16 bucket -> exactly two admit_chunk=8 prefill chunks
ADMIT_CHUNK = 8


@pytest.fixture(scope="module")
def replica_set():
    config = LlamaConfig.tiny(
        vocab_size=96, dim=64, n_layers=2, n_heads=4, n_kv_heads=2, hidden_dim=128,
        dtype=jnp.float32, param_dtype=jnp.float32,
    )
    module = Llama(config)
    params = module.init(jax.random.PRNGKey(1), jnp.zeros((1, 8), jnp.int32))["params"]
    mesh = MeshSpec(data=2, model=2).build(devices=jax.devices()[:4])
    cfg = GenerationConfig(max_new_tokens=8, temperature=0.0, prompt_buckets=(16,))
    rs = ReplicaSet.build(
        module, params, cfg, mesh=mesh, partition_rules=llama_partition_rules(),
        slots=2, decode_chunk=4, admit_chunk=ADMIT_CHUNK,
    )
    yield rs
    rs.close()


@pytest.fixture
def served(replica_set):
    """The serve shape, in process: HTTP server + tracer + flight recorder in
    front of the dp=2 x tp=2 fleet, `/gen` streaming tokens out of it."""
    srv = HTTPServer()
    recorder = FlightRecorder(32)
    srv.tracer = Tracer(enabled=True, recorder=recorder)
    srv.metrics = ServingMetrics()

    async def gen_handler(body):
        prompt = json.loads(body)["prompt"]
        loop = asyncio.get_running_loop()
        stream = replica_set.submit(prompt)  # trace ambient in handler context
        tokens = await loop.run_in_executor(
            None, lambda: [int(t) for c in stream for t in np.asarray(c).ravel()]
        )
        return 200, {"tokens": tokens}, "application/json"

    srv.route("POST", "/gen", gen_handler)
    return srv, recorder


def test_traced_request_timeline_dp2_tp2(served):
    srv, recorder = served
    rng = np.random.default_rng(7)
    prompt = [int(t) for t in rng.integers(1, 96, size=PROMPT_LEN)]

    status, payload, _, extra = asyncio.run(
        srv.dispatch_with_headers(
            "POST", "/gen", json.dumps({"prompt": prompt}).encode(),
            {"x-request-id": "acceptance-1"},
        )
    )
    assert status == 200 and extra["X-Request-Id"] == "acceptance-1"
    assert len(payload["tokens"]) == 8

    snap = recorder.get("acceptance-1")
    assert snap is not None and snap["in_flight"] is False and snap["status"] == 200
    events = snap["events"]
    names = [e["event"] for e in events]

    # monotonic offsets: one clock, strictly non-decreasing across layers
    offsets = [e["t_ms"] for e in events]
    assert offsets == sorted(offsets)

    # routed-replica event carries which replica and the load it saw
    routed = next(e for e in events if e["event"] == "engine.routed")
    assert routed["replica"] in (0, 1) and routed["load"] >= 0

    # queue wait is on the admission event
    admission = next(e for e in events if e["event"] == "engine.admission_start")
    assert admission["queue_wait_ms"] >= 0

    # EVERY prefill chunk: 14 tokens pad to the 16 bucket -> chunks at 8, 16
    chunk_events = [e for e in events if e["event"] == "engine.prefill_chunk"]
    assert [c["pos"] for c in chunk_events] == [ADMIT_CHUNK, 2 * ADMIT_CHUNK]

    # per-emission events account for every streamed token
    emitted = sum(e["tokens"] for e in events if e["event"] == "engine.emit")
    assert emitted == len(payload["tokens"])
    assert "engine.first_token" in names and "engine.finish" in names
    assert names.index("engine.routed") < names.index("engine.admission_start")


def test_concurrent_traced_requests_route_across_replicas(served):
    srv, recorder = served
    rng = np.random.default_rng(11)
    prompts = [[int(t) for t in rng.integers(1, 96, size=PROMPT_LEN)] for _ in range(4)]

    def fire(i):
        return asyncio.run(
            srv.dispatch_with_headers(
                "POST", "/gen", json.dumps({"prompt": prompts[i]}).encode(),
                {"x-request-id": f"conc-{i}"},
            )
        )

    results = [None] * len(prompts)
    threads = [
        threading.Thread(target=lambda i=i: results.__setitem__(i, fire(i)))
        for i in range(len(prompts))
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=180)
    assert all(r is not None and r[0] == 200 for r in results)

    replicas_used = set()
    for i in range(len(prompts)):
        events = recorder.get(f"conc-{i}")["events"]
        routed = [e for e in events if e["event"] == "engine.routed"]
        assert routed, f"conc-{i} never routed"
        replicas_used.add(routed[-1]["replica"])
    assert replicas_used == {0, 1}  # least-loaded routing actually spread the fleet


_SAMPLE = re.compile(
    r"^[a-zA-Z_:][a-zA-Z0-9_:]*"
    r'(\{[a-zA-Z_][a-zA-Z0-9_]*="(\\.|[^"\\\n])*"(,[a-zA-Z_][a-zA-Z0-9_]*="(\\.|[^"\\\n])*")*\})?'
    r" (-?\d+(\.\d+)?([eE][+-]?\d+)?|[+-]?Inf|NaN)$"
)
_TYPE_LINE = re.compile(
    r"^# TYPE [a-zA-Z_:][a-zA-Z0-9_:]* (counter|gauge|summary|histogram|untyped)$"
)


def test_fleet_metrics_render_prometheus_clean(served, replica_set):
    srv, _ = served
    snapshot = srv.metrics.snapshot()
    snapshot["generation"] = replica_set.stats()  # the app's merged shape
    text = render_prometheus(snapshot)
    for line in text.rstrip("\n").splitlines():
        assert _TYPE_LINE.match(line) or _SAMPLE.match(line), f"bad line: {line!r}"
    assert "unionml_tpu_generation_replicas" in text
    assert 'index="1"' in text  # per-replica series labeled, not name-exploded
    assert "None" not in text
