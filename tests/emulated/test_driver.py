"""Train-driver + parallelism tests on the emulated 8-device CPU mesh.

The analog of the reference's cluster ring (SURVEY.md §4): multi-chip behavior without
hardware, via ``--xla_force_host_platform_device_count=8``.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
import optax
from flax import linen as nn
from flax.training import train_state

from unionml_tpu import MeshSpec, TrainerConfig, make_train_step
from unionml_tpu.parallel.sharding import batch_sharding, infer_fsdp_sharding
from unionml_tpu.train import evaluate, fit


class TinyMLP(nn.Module):
    width: int = 32

    @nn.compact
    def __call__(self, x):
        x = nn.Dense(self.width)(x)
        x = nn.relu(x)
        return nn.Dense(2)(x)


def _make_state(lr=1e-2, width=32):
    module = TinyMLP(width)
    params = module.init(jax.random.PRNGKey(0), jnp.zeros((1, 8)))["params"]
    return module, train_state.TrainState.create(apply_fn=module.apply, params=params, tx=optax.adam(lr))


def _make_data(n=1024, one_d_targets=False):
    rng = np.random.default_rng(0)
    w = rng.normal(size=(8,))
    X = rng.normal(size=(n, 8)).astype("float32")
    y = (X @ w > 0).astype("int32")
    return [X, y if one_d_targets else y[:, None]]


def _loss(module):
    def loss_fn(params, batch):
        X, y = batch
        logits = module.apply({"params": params}, X)
        labels = y.reshape(-1).astype(jnp.int32)
        return optax.softmax_cross_entropy_with_integer_labels(logits, labels).mean()

    return loss_fn


def test_devices_emulated():
    assert len(jax.devices()) == 8


def test_fit_dp_mesh():
    module, state = _make_state()
    step = make_train_step(_loss(module))
    result = fit(state, step, _make_data(), TrainerConfig(epochs=2, batch_size=128, mesh=MeshSpec(data=-1)))
    assert result.steps == 16
    assert result.history[-1]["loss"] < 0.4
    assert result.samples_per_sec > 0
    assert result.compile_time_s > 0


def test_fit_one_dimensional_targets():
    """1-D label vectors must not crash batch placement (regression)."""
    module, state = _make_state()
    step = make_train_step(_loss(module))
    result = fit(state, step, _make_data(one_d_targets=True), TrainerConfig(epochs=1, batch_size=64))
    assert result.steps == 16


def test_fit_partial_final_batch():
    """drop_remainder=False with an indivisible final batch must not crash."""
    module, state = _make_state()
    step = make_train_step(_loss(module))
    data = _make_data(n=1000)
    result = fit(
        state, step, data, TrainerConfig(epochs=1, batch_size=128, drop_remainder=False, mesh=MeshSpec(data=-1))
    )
    assert result.steps == 8  # 7 full + 1 partial


def test_fit_grad_accumulation():
    module, state = _make_state()
    step = make_train_step(_loss(module), grad_accum_steps=4)
    result = fit(state, step, _make_data(), TrainerConfig(epochs=2, batch_size=128, mesh=MeshSpec(data=-1)))
    assert result.history[-1]["loss"] < 0.5
    # fit pinned the scan-carry/microbatch layouts (driver._pin_accum_shardings):
    # the grads carry follows the param shardings, the microbatch stack keeps
    # the batch layout with a leading accum dim, and the divisor counts the
    # batch-axis shards — the explicit layouts the dryrun's warning-free SPMD
    # assertion depends on
    param_sh, micro_sh, micro_div = step.pinned_shardings
    assert param_sh is not None and micro_sh is not None
    assert micro_sh.spec[0] is None  # accum dim replicated
    assert micro_div == 8  # data=-1 on 8 emulated devices


def test_fit_fsdp_shards_params():
    module, state = _make_state(width=1024)  # big enough to trip the fsdp threshold
    step = make_train_step(_loss(module))
    config = TrainerConfig(epochs=1, batch_size=128, mesh=MeshSpec(data=2, fsdp=4), fsdp_min_weight_size=1024)
    result = fit(state, step, _make_data(), config)
    kernel = result.state.params["Dense_0"]["kernel"]
    # the fsdp axis (size 4) should shard the largest divisible dim of the kernel
    assert "fsdp" in str(kernel.sharding.spec)


def test_evaluate_partial_batches():
    module, state = _make_state()
    step = make_train_step(_loss(module))
    data = _make_data(n=1001)
    state = fit(state, step, data, TrainerConfig(epochs=2, batch_size=128, mesh=MeshSpec(data=-1))).state

    def eval_step(state, batch):
        X, y = batch
        logits = module.apply({"params": state.params}, X)
        acc = (jnp.argmax(logits, -1) == y.reshape(-1)).mean()
        return {"accuracy": acc}

    metrics = evaluate(state, eval_step, data, batch_size=128, mesh=MeshSpec(data=-1))
    assert metrics["accuracy"] > 0.9


def test_evaluate_consumes_fsdp_sharded_state_in_place():
    """evaluate() compiles with the same resolved shardings as fit(): an
    FSDP-sharded state keeps its placement (no per-split reshard) and the
    metrics match an unsharded evaluation."""
    module, state = _make_state(width=1024)
    step = make_train_step(_loss(module))
    data = _make_data()
    mesh_spec = MeshSpec(data=2, fsdp=4)
    config = TrainerConfig(epochs=1, batch_size=128, mesh=mesh_spec, fsdp_min_weight_size=1024)
    trained = fit(state, step, data, config).state
    assert "fsdp" in str(trained.params["Dense_0"]["kernel"].sharding.spec)

    def eval_step(state, batch):
        X, y = batch
        logits = module.apply({"params": state.params}, X)
        return {"accuracy": (jnp.argmax(logits, -1) == y.reshape(-1)).mean()}

    sharded = evaluate(
        trained, eval_step, data, batch_size=128, mesh=mesh_spec, fsdp_min_weight_size=1024
    )
    plain = evaluate(trained, eval_step, data, batch_size=128, mesh=MeshSpec(data=-1))
    assert sharded["accuracy"] > 0.9
    np.testing.assert_allclose(sharded["accuracy"], plain["accuracy"], atol=1e-6)


def test_checkpoint_and_resume(tmp_path):
    module, state = _make_state()
    step = make_train_step(_loss(module))
    data = _make_data()
    ckpt_dir = str(tmp_path / "ckpt")

    full = fit(state, step, data, TrainerConfig(epochs=2, batch_size=128, shuffle=False, donate=False))

    _, state2 = _make_state()
    partial = fit(
        state2,
        step,
        data,
        TrainerConfig(
            epochs=1, batch_size=128, shuffle=False, donate=False,
            checkpoint_dir=ckpt_dir, checkpoint_every_steps=4,
        ),
    )
    assert partial.steps == 8
    _, state3 = _make_state()
    resumed = fit(
        state3,
        step,
        data,
        TrainerConfig(
            epochs=2, batch_size=128, shuffle=False, donate=False,
            checkpoint_dir=ckpt_dir, checkpoint_every_steps=4, resume=True,
        ),
    )
    # resumed from completed step 8, so only 8 more steps run
    assert resumed.steps == 8
    np.testing.assert_allclose(
        float(full.history[-1]["loss"]), float(resumed.history[-1]["loss"]), rtol=0.2
    )


def test_batch_sharding_handles_any_rank():
    mesh = MeshSpec(data=-1).build()
    sharding = batch_sharding(mesh)
    for shape in [(16,), (16, 4), (16, 4, 2)]:
        arr = jax.device_put(np.zeros(shape, dtype="float32"), sharding)
        assert arr.sharding.is_equivalent_to(sharding, len(shape))


def test_infer_fsdp_sharding_rules():
    mesh = MeshSpec(data=2, fsdp=4).build()
    params = {
        "big": np.zeros((1024, 64), dtype="float32"),
        "bias": np.zeros((64,), dtype="float32"),
    }
    shardings = infer_fsdp_sharding(params, mesh, min_weight_size=1024)
    assert "fsdp" in str(shardings["big"].spec)
    assert str(shardings["bias"].spec) == "PartitionSpec()"


def test_device_data_mode_matches_host_path():
    module, state = _make_state()
    step = make_train_step(_loss(module))
    data = _make_data()
    host = fit(state, step, data, TrainerConfig(epochs=2, batch_size=128, shuffle=False, donate=False))
    _, state2 = _make_state()
    dev = fit(
        state2,
        step,
        data,
        TrainerConfig(epochs=2, batch_size=128, shuffle=False, donate=False, device_data=True, steps_per_call=3),
    )
    assert dev.steps == host.steps == 16
    np.testing.assert_allclose(
        float(dev.history[-1]["loss"]), float(host.history[-1]["loss"]), rtol=1e-4
    )


def test_device_data_small_dataset_still_trains():
    """steps_per_call larger than the schedule must not silently train nothing."""
    module, state = _make_state()
    step = make_train_step(_loss(module))
    result = fit(
        state,
        step,
        _make_data(n=256),
        TrainerConfig(epochs=1, batch_size=64, device_data=True, steps_per_call=50),
    )
    assert result.steps == 4


def test_device_data_log_trigger_with_stride(tmp_path):
    module, state = _make_state()
    step = make_train_step(_loss(module))
    result = fit(
        state,
        step,
        _make_data(),
        TrainerConfig(epochs=2, batch_size=128, device_data=True, steps_per_call=3, log_every_steps=5),
    )
    assert len(result.history) >= 3  # crossing semantics: logs fire despite stride 3


def test_fit_with_flax_logical_partitioning_metadata():
    """A module annotated with nn.with_partitioning carries its layout in the
    params tree; fit() maps the logical names to mesh axes via
    logical_axis_rules, unboxes, and trains with those placements (SURVEY.md
    §7 hard part 3 — no regex tables needed)."""

    class AnnotatedMLP(nn.Module):
        @nn.compact
        def __call__(self, x):
            x = nn.Dense(
                256,
                kernel_init=nn.with_partitioning(nn.initializers.lecun_normal(), ("inp", "hidden")),
            )(x)
            x = nn.relu(x)
            return nn.Dense(
                2,
                kernel_init=nn.with_partitioning(nn.initializers.lecun_normal(), ("hidden", None)),
            )(x)

    module = AnnotatedMLP()
    variables = module.init(jax.random.PRNGKey(0), jnp.zeros((1, 8)))
    params = variables["params"]
    # metadata boxes really are in the tree
    assert isinstance(params["Dense_0"]["kernel"], nn.Partitioned)

    state = train_state.TrainState.create(
        apply_fn=module.apply, params=params, tx=optax.adam(1e-2)
    )

    result = fit(
        state,
        make_train_step(_loss(module)),
        _make_data(),
        TrainerConfig(
            epochs=2,
            batch_size=128,
            mesh=MeshSpec(data=2, fsdp=2, model=2),
            logical_axis_rules=[("hidden", "model"), ("inp", "fsdp")],
        ),
    )
    kernel0 = result.state.params["Dense_0"]["kernel"]
    assert not isinstance(kernel0, nn.Partitioned)  # unboxed for training
    assert str(kernel0.sharding.spec) == "PartitionSpec('fsdp', 'model')"
    # optimizer state inherited the same placement through the boxed tree
    mu0 = result.state.opt_state[0].mu["Dense_0"]["kernel"]
    assert str(mu0.sharding.spec) == "PartitionSpec('fsdp', 'model')"
    assert result.history[-1]["loss"] < 0.5


def test_logical_metadata_names_used_as_mesh_axes_without_rules():
    """Without logical_axis_rules, Partitioned names are mesh axis names directly;
    names not present in the mesh replicate their dim."""
    from unionml_tpu.parallel import combine_fsdp_tp, unbox_partitioned

    mesh = MeshSpec(data=4, model=2).build()
    kernel = nn.Partitioned(jnp.zeros((8, 16)), names=("missing_axis", "model"))
    tree = {"layer": {"kernel": kernel, "bias": jnp.zeros((16,))}}
    shardings = combine_fsdp_tp(tree, mesh, None, logical_rules=None)
    assert str(shardings["layer"]["kernel"].spec) == "PartitionSpec(None, 'model')"
    unboxed = unbox_partitioned(tree)
    assert unboxed["layer"]["kernel"].shape == (8, 16)


def test_fit_reports_memory_stats_or_none():
    """FitResult carries the §5.5 HBM accounting: a dict of byte counters on
    backends that expose memory_stats, None on backends that don't (CPU)."""
    module, state = _make_state()
    result = fit(
        state, make_train_step(_loss(module)), _make_data(n=256),
        TrainerConfig(epochs=1, batch_size=128),
    )
    assert result.memory_stats is None or (
        isinstance(result.memory_stats, dict)
        and all(isinstance(v, int) for v in result.memory_stats.values())
    )


def test_evaluate_keeps_existing_placement_of_trained_state(monkeypatch):
    """The state fit() returns (logical-metadata layout, boxes already stripped)
    must be consumed in place by evaluate(): the shardings handed to placement
    are the leaves' EXISTING shardings, not a fresh FSDP resolution — asserted
    by spying on shard_pytree (numerics alone cannot detect a reshard)."""

    class Annotated(nn.Module):
        @nn.compact
        def __call__(self, x):
            x = nn.Dense(
                256,
                kernel_init=nn.with_partitioning(nn.initializers.lecun_normal(), ("inp", "hidden")),
            )(x)
            return nn.Dense(2)(nn.relu(x))

    module = Annotated()
    params = module.init(jax.random.PRNGKey(0), jnp.zeros((1, 8)))["params"]
    state = train_state.TrainState.create(apply_fn=module.apply, params=params, tx=optax.adam(1e-2))
    mesh_spec = MeshSpec(data=2, fsdp=2, model=2)
    result = fit(
        state,
        make_train_step(_loss(module)),
        _make_data(),
        TrainerConfig(
            epochs=1, batch_size=128, mesh=mesh_spec,
            logical_axis_rules=[("hidden", "model"), ("inp", "fsdp")],
        ),
    )
    trained_spec = str(result.state.params["Dense_0"]["kernel"].sharding.spec)
    assert trained_spec == "PartitionSpec('fsdp', 'model')"

    def eval_step(st, batch):
        X, y = batch
        logits = module.apply({"params": st.params}, X)
        return {"accuracy": (jnp.argmax(logits, -1) == y.reshape(-1)).mean()}

    import unionml_tpu.train.driver as driver_mod

    captured = {}
    real_shard_pytree = driver_mod.shard_pytree

    def spying_shard_pytree(pytree, shardings):
        captured["kernel_spec"] = str(shardings.params["Dense_0"]["kernel"].spec)
        return real_shard_pytree(pytree, shardings)

    monkeypatch.setattr(driver_mod, "shard_pytree", spying_shard_pytree)
    # no rules passed at all: existing placement must be honored, not re-derived
    metrics = evaluate(result.state, eval_step, _make_data(), batch_size=128, mesh=mesh_spec)
    assert metrics["accuracy"] > 0.9
    assert captured["kernel_spec"] == trained_spec  # placed onto its OWN sharding


def test_fit_dcn_data_outer_axis_matches_flat_dp():
    """Cross-slice layout: a 2-slice ``dcn_data`` outer axis wrapping an
    intra-slice data*fsdp mesh must train to the same loss trajectory as flat
    DP over the same 8 devices — only the gradient all-reduce spans the outer
    axis, params/optimizer state replicate over it (mesh.py's scaling-book
    recipe)."""
    module, state = _make_state()
    step = make_train_step(_loss(module))
    data = _make_data()

    flat = fit(state, step, data, TrainerConfig(epochs=1, batch_size=128, mesh=MeshSpec(data=-1)))
    _, state2 = _make_state()
    dcn = fit(
        state2,
        step,
        data,
        TrainerConfig(
            epochs=1, batch_size=128,
            mesh=MeshSpec(dcn_data=2, data=2, fsdp=2), fsdp_min_weight_size=256,
        ),
    )
    assert dcn.steps == flat.steps
    np.testing.assert_allclose(
        dcn.history[-1]["loss"], flat.history[-1]["loss"], rtol=1e-4
    )
