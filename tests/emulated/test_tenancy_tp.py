"""Multi-tenant QoS over a dp=2×tp=2 replica fleet on the emulated mesh.

The acceptance leg for docs/serving.md "Multi-tenant QoS": with two tenants at
EQUAL weight offering the same load, the fleet serves them to (exactly) equal
token share and their streams stay token-identical to a solo reference — the
QoS layer redirects scheduling, never tokens — while a ZERO-weight burst
tenant is held at its request bucket's rate: its admitted count equals the
bucket capacity (frozen clock: no refill), the rest shed 429 with a
refill-derived Retry-After, and the weighted tenants' service is unaffected.
"""

import threading

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from unionml_tpu.models import GenerationConfig, Generator, Llama, LlamaConfig, llama_partition_rules
from unionml_tpu.parallel import MeshSpec
from unionml_tpu.serving import ReplicaSet, TenantRegistry, TenantSpec
from unionml_tpu.serving.overload import TenantThrottled

pytestmark = pytest.mark.skipif(len(jax.devices()) < 8, reason="needs 8 emulated devices")


@pytest.fixture(scope="module")
def tiny():
    config = LlamaConfig.tiny(
        vocab_size=96, dim=64, n_layers=2, n_heads=4, n_kv_heads=2, hidden_dim=128,
        dtype=jnp.float32, param_dtype=jnp.float32,
    )
    module = Llama(config)
    params = module.init(jax.random.PRNGKey(1), jnp.zeros((1, 8), jnp.int32))["params"]
    return module, params


def _cfg(**overrides):
    kwargs = dict(max_new_tokens=8, temperature=0.0, prompt_buckets=(16,))
    kwargs.update(overrides)
    return GenerationConfig(**kwargs)


def _drain(stream):
    return [int(t) for chunk in stream for t in np.asarray(chunk).ravel()]


def _slow_decode(engine, dispatch_s=0.02):
    real = engine.gen._decode

    def slow(*args, _real=real, **kwargs):
        import time

        time.sleep(dispatch_s)
        return _real(*args, **kwargs)

    engine.gen._decode = slow


def test_tp2_priority_preemption_resumes_token_identical(tiny):
    """A high-priority admission on a full tp=2 paged engine preempts exactly
    one lowest-priority resident, and the victim's resumed stream is
    token-identical to an unpreempted run — the exact-width-resume contract
    held under TP sharding."""
    import time

    from unionml_tpu.serving import ContinuousBatcher

    module, params = tiny
    cfg = _cfg(max_new_tokens=24)
    mesh = MeshSpec(model=2).build(devices=jax.devices()[:2])
    gen = Generator(module, params, cfg, mesh=mesh, partition_rules=llama_partition_rules())
    reference = {
        tuple(p): list(map(int, gen([p])[0]))
        for p in ([3, 1, 4, 1, 5], [7, 7, 1])
    }
    engine = ContinuousBatcher(gen, slots=1, decode_chunk=2, block_size=16, pool_blocks=16)
    try:
        engine.warmup()
        _slow_decode(engine)
        results = {}

        def consume(name, stream):
            results[name] = _drain(stream)

        batch = engine.submit([3, 1, 4, 1, 5], priority=2)
        thread = threading.Thread(target=consume, args=("batch", batch))
        thread.start()
        deadline = time.monotonic() + 10.0
        while engine.occupancy()[0] < 1 and time.monotonic() < deadline:
            time.sleep(0.005)
        high_out = _drain(engine.submit([7, 7, 1], priority=0))
        thread.join(timeout=120)
        assert engine.priority_preemptions == 1
        assert high_out == reference[(7, 7, 1)]
        assert results["batch"] == reference[(3, 1, 4, 1, 5)]
    finally:
        engine.close()


A_PROMPTS = [[3, 1, 4, 1, 5], [2, 7, 1, 8], [9, 3, 9], [6, 2, 6, 4, 3]]
B_PROMPTS = [[5, 5, 5], [1, 2, 3, 4, 5, 6], [8, 1], [4, 4, 7, 2]]


def test_equal_weight_share_and_zero_weight_bucket_hold(tiny):
    module, params = tiny
    cfg = _cfg()
    mesh = MeshSpec(data=2, model=2).build(devices=jax.devices()[:4])
    clk = [0.0]  # frozen registry clock: the burst bucket never refills
    registry = TenantRegistry(
        {
            "alpha": TenantSpec(weight=1),
            "beta": TenantSpec(weight=1),
            "burst": TenantSpec(weight=0, req_per_s=2.0, burst_s=2.0),  # cap = 4
        },
        clock=lambda: clk[0],
    )
    reference = {
        tuple(p): list(map(int, Generator(module, params, cfg)([p])[0]))
        for p in A_PROMPTS + B_PROMPTS
    }
    fleet = ReplicaSet.build(
        module, params, cfg, mesh=mesh, partition_rules=llama_partition_rules(),
        slots=2, decode_chunk=4, max_waiting=64, tenancy=registry,
    )
    try:
        assert fleet.replicas == 2
        streams = []
        labels = []
        # interleaved offered load: alpha and beta compete for every slot
        for a, b in zip(A_PROMPTS, B_PROMPTS):
            streams.append(fleet.submit(a, tenant="alpha"))
            labels.append("alpha")
            streams.append(fleet.submit(b, tenant="beta"))
            labels.append("beta")
        # the zero-weight burst tenant floods 10 requests: exactly the bucket
        # capacity (4 at a frozen clock) admit, the rest shed with the
        # bucket's own retry hint
        burst_admitted, retries = [], []
        for i in range(10):
            try:
                burst_admitted.append(fleet.submit([10 + i], tenant="burst", max_new_tokens=2))
            except TenantThrottled as exc:
                retries.append(exc.retry_after_s)
        assert len(burst_admitted) == 4
        assert len(retries) == 6 and all(r == pytest.approx(0.5, rel=0.05) for r in retries)

        results = [None] * len(streams)

        def worker(i):
            results[i] = _drain(streams[i])

        threads = [threading.Thread(target=worker, args=(i,)) for i in range(len(streams))]
        for t in threads:
            t.start()
        burst_tokens = sum(len(_drain(s)) for s in burst_admitted)
        for t in threads:
            t.join(timeout=180)

        # equal weight -> equal service: every stream of both tenants
        # completes, token-identical to the solo reference
        for label, prompt, out in zip(
            labels, [p for pair in zip(A_PROMPTS, B_PROMPTS) for p in pair], results
        ):
            assert out == reference[tuple(prompt)], (label, prompt)
        per_tenant = registry.stats()["per_tenant"]
        assert per_tenant["alpha"]["generated_tokens"] == per_tenant["beta"]["generated_tokens"]
        assert per_tenant["alpha"]["admitted"] == per_tenant["beta"]["admitted"] == 4
        # the burst tenant was held at its bucket: 4 admitted, 6 shed, and its
        # served tokens are bounded by its own budget — not by crowding out
        # the weighted tenants
        assert per_tenant["burst"]["admitted"] == 4
        assert per_tenant["burst"]["shed"] == 6
        assert burst_tokens == 4 * 2
        fleet_stats = fleet.stats()
        assert fleet_stats["tenancy"]["shed_tenant_limit"] == 6
    finally:
        fleet.close()
