"""Data-parallel replica serving on the emulated 8-device mesh.

Oracle: a dp=2 x tp=2 :class:`ReplicaSet` serving concurrent streams must emit
EXACTLY the tokens of a single-device sequential ``Generator.__call__([p])``
run per prompt (greedy, f32) — replication and least-loaded routing must be
invisible in the output. Also pins the ``ContinuousBatcher`` delegation paths
(dp mesh / ``--dp-replicas`` env) and the per-replica occupancy surface.
"""

import threading

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from unionml_tpu.models import GenerationConfig, Generator, Llama, LlamaConfig, llama_partition_rules
from unionml_tpu.parallel import MeshSpec
from unionml_tpu.serving import ContinuousBatcher, ReplicaSet
from unionml_tpu.serving.replicas import dp_extent, slice_mesh

pytestmark = pytest.mark.skipif(len(jax.devices()) < 8, reason="needs 8 emulated devices")

PROMPTS = [[3, 1, 4, 1, 5], [9, 2, 6, 5, 3, 5, 8, 9], [7, 1], [6, 6, 6, 2], [5, 5], [8]]


@pytest.fixture(scope="module")
def tiny():
    config = LlamaConfig.tiny(
        vocab_size=96, dim=64, n_layers=2, n_heads=4, n_kv_heads=2, hidden_dim=128,
        dtype=jnp.float32, param_dtype=jnp.float32,
    )
    module = Llama(config)
    params = module.init(jax.random.PRNGKey(1), jnp.zeros((1, 8), jnp.int32))["params"]
    return module, params


def _cfg():
    return GenerationConfig(max_new_tokens=8, temperature=0.0, prompt_buckets=(16,))


def _expected(module, params):
    gen = Generator(module, params, _cfg())
    return [list(gen([p])[0]) for p in PROMPTS]


def _drain_concurrently(streams):
    results = [None] * len(streams)

    def worker(i):
        results[i] = [int(t) for chunk in streams[i] for t in np.asarray(chunk).ravel()]

    threads = [threading.Thread(target=worker, args=(i,)) for i in range(len(streams))]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=180)
    return results


def _dp_mesh():
    # dp=2 x tp=2 over half the emulated 8-device mesh (the acceptance shape)
    return MeshSpec(data=2, model=2).build(devices=jax.devices()[:4])


def test_slice_mesh_cuts_batch_axes_into_tp_submeshes():
    mesh = MeshSpec(data=2, fsdp=2, model=2).build()  # dp extent 4 over all 8 devices
    assert dp_extent(mesh) == 4
    submeshes = slice_mesh(mesh)
    assert len(submeshes) == 4
    seen = set()
    for sub in submeshes:
        assert dp_extent(sub) == 1
        assert int(sub.shape["model"]) == 2  # TP extent preserved
        seen.update(d.id for d in np.asarray(sub.devices).ravel())
    assert len(seen) == 8  # replicas partition the devices, no overlap
    # a DIVIDING smaller count groups batch slices per replica, folding the
    # leftover extent into the model axis (fewer, fatter TP replicas)
    grouped = slice_mesh(mesh, replicas=2)
    assert len(grouped) == 2
    grouped_ids = set()
    for sub in grouped:
        assert dp_extent(sub) == 1
        assert int(sub.shape["model"]) == 4  # 2 grouped slices x tp=2
        grouped_ids.update(d.id for d in np.asarray(sub.devices).ravel())
    assert len(grouped_ids) == 8
    # a NON-dividing count raises a clear error naming the batch-axis extents
    # (historically an opaque reshape error deep in mesh construction)
    with pytest.raises(ValueError, match="data=2, fsdp=2"):
        slice_mesh(mesh, replicas=3)


def test_replica_set_streams_match_single_engine_reference(tiny):
    module, params = tiny
    expected = _expected(module, params)
    replica_set = ReplicaSet.build(
        module, params, _cfg(), mesh=_dp_mesh(), partition_rules=llama_partition_rules(),
        slots=2, decode_chunk=4,
    )
    try:
        assert replica_set.replicas == 2
        streams = [replica_set.submit(p) for p in PROMPTS]
        assert _drain_concurrently(streams) == expected
        stats = replica_set.stats()
        # least-loaded dispatch is observable: both replicas took work, and the
        # /metrics surface reports per-replica occupancy + routing telemetry
        assert all(n >= 1 for n in stats["scheduler"]["submitted"])
        assert sum(stats["scheduler"]["submitted"]) == len(PROMPTS)
        assert stats["replicas"] == 2 and len(stats["per_replica"]) == 2
        for entry in stats["per_replica"]:
            assert {"slots", "resident", "waiting", "decode_dispatches"} <= set(entry)
        loads = replica_set.replica_loads()
        assert [entry["replica"] for entry in loads] == [0, 1]
        assert all(entry["free_slots"] == 2 for entry in loads)  # all drained
    finally:
        replica_set.close()


def test_continuous_batcher_delegates_dp_mesh_to_replica_set(tiny):
    """The old hard data/fsdp rejection is now delegation: constructing the
    engine over a dp mesh returns a ReplicaSet with the same surface."""
    module, params = tiny
    expected = _expected(module, params)
    gen = Generator(
        module, params, _cfg(), mesh=_dp_mesh(), partition_rules=llama_partition_rules()
    )
    engine = ContinuousBatcher(gen, slots=2, decode_chunk=4)
    try:
        assert isinstance(engine, ReplicaSet) and engine.replicas == 2
        streams = [engine.submit(p) for p in PROMPTS[:3]]
        assert _drain_concurrently(streams) == expected[:3]
    finally:
        engine.close()


def test_dp_replicas_env_replicates_meshless_engine(tiny, monkeypatch):
    """The serve CLI's --dp-replicas export replicates a meshless generator
    over distinct emulated devices — no app code changes."""
    from unionml_tpu.defaults import SERVE_DP_REPLICAS_ENV_VAR

    module, params = tiny
    expected = _expected(module, params)
    monkeypatch.setenv(SERVE_DP_REPLICAS_ENV_VAR, "2")
    engine = ContinuousBatcher(Generator(module, params, _cfg()), slots=2, decode_chunk=4)
    try:
        assert isinstance(engine, ReplicaSet) and engine.replicas == 2
        devices = {
            np.asarray(b.gen.mesh.devices).ravel()[0].id for b in engine.batchers
        }
        assert len(devices) == 2  # each replica owns its own device
        streams = [engine.submit(p) for p in PROMPTS[:4]]
        assert _drain_concurrently(streams) == expected[:4]
        assert all(n >= 1 for n in engine.stats()["scheduler"]["submitted"])
    finally:
        engine.close()
