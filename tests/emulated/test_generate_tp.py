"""Sharded generation on the emulated 8-device CPU mesh.

Oracle: generation over a (data, model) mesh — megatron-TP params via
``llama_partition_rules`` and the KV cache sharded batch-over-data /
heads-over-model — must emit exactly the tokens of the unsharded single-device
run. XLA inserts the collectives; the engine only places data.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from unionml_tpu.models import GenerationConfig, Generator, Llama, LlamaConfig, llama_partition_rules
from unionml_tpu.parallel import MeshSpec

pytestmark = pytest.mark.skipif(len(jax.devices()) < 8, reason="needs 8 emulated devices")


def _tiny():
    config = LlamaConfig.tiny(
        vocab_size=96, dim=64, n_layers=2, n_heads=4, n_kv_heads=2, hidden_dim=128,
        dtype=jnp.float32, param_dtype=jnp.float32,
    )
    module = Llama(config)
    params = module.init(jax.random.PRNGKey(1), jnp.zeros((1, 8), jnp.int32))["params"]
    return module, params


def _until_eos(row, eos):
    """Solo-run rows pad past eos; streams stop at it — truncate for comparison."""
    out = []
    for t in row:
        out.append(int(t))
        if t == eos:
            break
    return out


def _letters_cs(pattern):
    """a-z char vocab over the tiny model's 96 ids (last id = eos) + one
    compiled grammar — shared by the constraint-composition tests."""
    from unionml_tpu.models import ConstraintSet, compile_regex

    texts = [""] * 96
    for i in range(26):
        texts[1 + i] = chr(ord("a") + i)
    eos = 95
    return ConstraintSet([compile_regex(pattern, texts, eos_id=eos)]), eos


@pytest.mark.parametrize("spec", [dict(data=4, model=2), dict(model=4), dict(data=4, fsdp=2)])
def test_sharded_generation_matches_unsharded(spec):
    module, params = _tiny()
    cfg = GenerationConfig(max_new_tokens=8, temperature=0.0, prompt_buckets=(16,))
    prompts = [[3, 1, 4, 1, 5], [9, 2, 6, 5, 3, 5, 8, 9], [7, 1], [6, 6, 6, 2]]

    expected = Generator(module, params, cfg)(prompts)
    mesh = MeshSpec(**spec).build()
    sharded = Generator(module, params, cfg, mesh=mesh, partition_rules=llama_partition_rules())
    np.testing.assert_array_equal(sharded(prompts), expected)
    # a single prompt must also shard (batch pads up to the data-axis size)
    np.testing.assert_array_equal(sharded([prompts[0]]), expected[:1])


@pytest.mark.parametrize("impl,axes", [("ring", dict(data=1, sequence=8)), ("ulysses", dict(data=2, sequence=4))])
def test_sequence_parallel_prefill_matches_plain_generation(impl, axes):
    """Long-context handoff: prefill runs the decoder sequence-parallel under
    shard_map (ring KV rotation / ulysses all-to-all), the cache is assembled
    from the sown per-layer K/V, and decode proceeds on the ordinary cached
    path — tokens must equal the plain single-device engine."""
    module, params = _tiny()
    prompts = [[3, 1, 4, 1, 5, 9, 2, 6, 5, 3], [7, 1, 8], [2, 8, 1, 8, 2, 8], [4, 6]]

    plain = Generator(
        module, params, GenerationConfig(max_new_tokens=8, temperature=0.0, prompt_buckets=(16,))
    )(prompts)

    mesh = MeshSpec(**axes).build()
    sp = Generator(
        module, params,
        GenerationConfig(max_new_tokens=8, temperature=0.0, prompt_buckets=(16,), sp_prefill=impl),
        mesh=mesh,
    )
    np.testing.assert_array_equal(sp(prompts), plain)


def test_sharded_beam_search_matches_unsharded():
    """Beam search over a TP/data mesh (beams = batch rows, cache rows gathered
    to surviving parents under sharding) must pick the same sequences."""
    module, params = _tiny()
    cfg = GenerationConfig(max_new_tokens=6, temperature=0.0, prompt_buckets=(16,))
    prompts = [[3, 1, 4, 1, 5], [9, 2, 6]]

    expected = Generator(module, params, cfg).beam_search(prompts, num_beams=4)
    mesh = MeshSpec(data=4, model=2).build()
    sharded = Generator(module, params, cfg, mesh=mesh, partition_rules=llama_partition_rules())
    np.testing.assert_array_equal(sharded.beam_search(prompts, num_beams=4), expected)


def test_expert_parallel_generation_matches_unsharded():
    """MoE decoder served expert-parallel: stacked expert FFN weights sharded
    P('expert', ...) while the KV cache shards batch-over-data — tokens must
    equal the unsharded run (ample capacity: routing is drop-free on both paths)."""
    from unionml_tpu.models import MoEConfig, MoETransformer, moe_partition_rules

    config = MoEConfig.tiny(
        vocab_size=64, dim=64, n_layers=2, n_heads=4, n_kv_heads=2, hidden_dim=96,
        n_experts=4, k=2, capacity_factor=8.0, dtype=jnp.float32, param_dtype=jnp.float32,
    )
    module = MoETransformer(config)
    params = module.init(jax.random.PRNGKey(3), jnp.zeros((1, 8), jnp.int32))["params"]
    cfg = GenerationConfig(max_new_tokens=6, temperature=0.0, prompt_buckets=(16,))
    prompts = [[3, 1, 4, 1], [5, 9, 2], [6, 5], [3, 5, 8, 9]]

    expected = Generator(module, params, cfg)(prompts)
    mesh = MeshSpec(data=2, expert=4).build()
    sharded = Generator(module, params, cfg, mesh=mesh, partition_rules=moe_partition_rules())
    np.testing.assert_array_equal(sharded(prompts), expected)


def test_quantized_sharded_generation_matches_quantized_unsharded():
    """int8 weights + int8 KV cache + TP mesh: the QuantizedTensor pytree and
    the cache's scale planes must place under the partition rules and emit the
    same tokens as quantized single-device generation."""
    module, params = _tiny()
    cfg = GenerationConfig(
        max_new_tokens=8, temperature=0.0, prompt_buckets=(16,), kv_cache_dtype="int8"
    )
    prompts = [[3, 1, 4, 1, 5], [9, 2, 6], [7, 1, 8, 2], [2, 7]]

    expected = Generator(module, params, cfg, quantize="int8")(prompts)
    mesh = MeshSpec(data=2, fsdp=2, model=2).build()
    sharded = Generator(
        module, params, cfg, mesh=mesh, partition_rules=llama_partition_rules(), quantize="int8"
    )
    np.testing.assert_array_equal(sharded(prompts), expected)


@pytest.mark.parametrize("impl", ["ring", "ulysses"])
def test_sp_prefix_cache_composes(impl):
    """sp_prefill + prefix caching: the LONG shared prefix prefills
    sequence-parallel (inside cache_prefix), per-request suffixes go through
    the offset chunked path, and the emitted tokens equal the plain engine's
    full-prompt run."""
    import dataclasses

    module, params = _tiny()
    base = GenerationConfig(max_new_tokens=6, temperature=0.0, prompt_buckets=(8, 32))
    prefix = [(i * 7) % 90 + 1 for i in range(24)]  # long enough to shard over 8
    suffixes = [[3, 1, 4], [9, 2, 6, 5]]
    expected = Generator(module, params, base)([prefix + s for s in suffixes])

    mesh = MeshSpec(data=1, sequence=8 if impl == "ring" else 4, model=2 if impl == "ulysses" else 1).build()
    sp_gen = Generator(
        module,
        params,
        dataclasses.replace(base, sp_prefill=impl),
        mesh=mesh,
        partition_rules=llama_partition_rules() if impl == "ulysses" else None,
    )
    cached = sp_gen.cache_prefix(prefix)
    assert cached.length == len(prefix)
    np.testing.assert_array_equal(sp_gen(suffixes, prefix=cached), expected)


def test_continuous_batching_over_tp_mesh():
    """Serving deployment shape: a ContinuousBatcher whose Generator is
    tensor-parallel over a model axis — concurrent streams share sharded decode
    dispatches and still emit exactly the unsharded engine's tokens."""
    from unionml_tpu.serving import ContinuousBatcher

    module, params = _tiny()
    cfg = GenerationConfig(max_new_tokens=8, temperature=0.0, prompt_buckets=(16,))
    prompts = [[3, 1, 4, 1, 5], [9, 2, 6], [7, 1]]
    expected = [list(r) for r in Generator(module, params, cfg)(prompts)]

    mesh = MeshSpec(data=1, model=4).build(jax.devices()[:4])
    sharded = Generator(module, params, cfg, mesh=mesh, partition_rules=llama_partition_rules())
    batcher = ContinuousBatcher(sharded, slots=3, decode_chunk=4)
    try:
        streams = [batcher.submit(p) for p in prompts]
        results = [
            [int(t) for chunk in s for t in np.asarray(chunk).ravel()] for s in streams
        ]
        assert results == expected
    finally:
        batcher.close()


def test_sharded_constrained_generation_matches_unsharded():
    """Constraints x TP: the DFA tables replicate over the mesh (tiny int32/bool
    arrays), the per-row state rides the sharded decode carry, and tokens equal
    the unsharded constrained run — grammar masking adds no sharding hazards."""
    module, params = _tiny()
    cs, eos = _letters_cs(r"[a-c]{2,6}")
    cfg = GenerationConfig(
        max_new_tokens=8, temperature=0.0, eos_id=eos, prompt_buckets=(16,), constraints=cs
    )
    prompts = [[3, 1, 4, 1, 5], [9, 2, 6, 5], [7, 1], [6, 6, 6, 2]]
    gids = [1, 0, 1, 0]

    expected = Generator(module, params, cfg)(prompts, constraint=gids)
    mesh = MeshSpec(data=4, model=2).build()
    sharded = Generator(module, params, cfg, mesh=mesh, partition_rules=llama_partition_rules())
    np.testing.assert_array_equal(sharded(prompts, constraint=gids), expected)


def test_sequence_parallel_prefill_composes_with_constraints():
    """Long-context x grammar: the constrained first token is sampled inside
    the sequence-parallel prefill (the cstate tail threads through sp_prefill),
    and decode continues masking — tokens equal the plain constrained engine."""
    module, params = _tiny()
    cs, eos = _letters_cs(r"[a-c]{2,6}")
    base = GenerationConfig(
        max_new_tokens=6, temperature=0.0, eos_id=eos, prompt_buckets=(16,), constraints=cs
    )
    prompts = [[3, 1, 4, 1, 5, 9, 2, 6], [7, 1, 8], [2, 8, 1, 8], [4, 6]]
    gids = [1, 0, 1, 0]

    plain = Generator(module, params, base)(prompts, constraint=gids)
    import dataclasses

    mesh = MeshSpec(data=2, sequence=4).build()
    sp = Generator(module, params, dataclasses.replace(base, sp_prefill="ring"), mesh=mesh)
    np.testing.assert_array_equal(sp(prompts, constraint=gids), plain)


def test_continuous_batching_constrained_over_tp_mesh():
    """Batcher x TP x grammar: per-request grammars through the shared decode
    loop against model-axis-sharded params/KV equal the unsharded constrained
    solo runs (the dryrun pins the unconstrained TP batcher; this is the cross)."""
    from unionml_tpu.serving import ContinuousBatcher

    module, params = _tiny()
    cs, eos = _letters_cs(r"[a-c]{2,6}")
    cfg = GenerationConfig(
        max_new_tokens=6, temperature=0.0, eos_id=eos, prompt_buckets=(16,), constraints=cs
    )
    prompts = [[3, 1, 4, 1], [9, 2, 6], [7, 1]]
    gids = [1, 0, 1]
    plain = Generator(module, params, cfg)
    solo = [_until_eos(plain([p], constraint=g)[0], eos) for p, g in zip(prompts, gids)]

    mesh = MeshSpec(data=1, model=2).build(jax.devices()[:2])
    tp_gen = Generator(module, params, cfg, mesh=mesh, partition_rules=llama_partition_rules())
    batcher = ContinuousBatcher(tp_gen, slots=2, decode_chunk=2)
    try:
        streams = [batcher.submit(p, constraint=g) for p, g in zip(prompts, gids)]
        for stream, ref in zip(streams, solo):
            got = [int(t) for chunk in stream for t in np.atleast_1d(chunk)]
            assert got == ref
    finally:
        batcher.close()


def test_continuous_batching_with_sp_prefill():
    """Long-context admission (the round-4 hole at continuous.py): each
    admission's batch-1 row prefills ring-sequence-parallel over the mesh's
    sequence axis, pastes into the pool, and concurrent streams equal the plain
    single-device engine's tokens."""
    import dataclasses

    from unionml_tpu.serving import ContinuousBatcher

    module, params = _tiny()
    cfg = GenerationConfig(max_new_tokens=8, temperature=0.0, prompt_buckets=(16,))
    prompts = [[3, 1, 4, 1, 5, 9, 2, 6, 5, 3], [9, 2, 6], [7, 1, 8, 2, 8, 1]]
    expected = [list(r) for r in Generator(module, params, cfg)(prompts)]

    mesh = MeshSpec(data=1, sequence=4).build(jax.devices()[:4])
    sp_gen = Generator(module, params, dataclasses.replace(cfg, sp_prefill="ring"), mesh=mesh)
    batcher = ContinuousBatcher(sp_gen, slots=2, decode_chunk=3)
    try:
        streams = [batcher.submit(p) for p in prompts]
        results = [
            [int(t) for chunk in s for t in np.asarray(chunk).ravel()] for s in streams
        ]
        assert results == expected
    finally:
        batcher.close()


def test_continuous_batching_sp_prefill_paged():
    """sp admission x paged pool: the ring-prefilled row scatters into pool
    blocks like any dense row — the two round-4 composition holes close
    together, not just separately."""
    import dataclasses

    from unionml_tpu.serving import ContinuousBatcher

    module, params = _tiny()
    cfg = GenerationConfig(max_new_tokens=6, temperature=0.0, prompt_buckets=(8,))
    prompts = [[3, 1, 4, 1, 5], [9, 2, 6], [7, 1]]
    expected = [list(r) for r in Generator(module, params, cfg)(prompts)]

    mesh = MeshSpec(data=1, sequence=2).build(jax.devices()[:2])
    sp_gen = Generator(module, params, dataclasses.replace(cfg, sp_prefill="ring"), mesh=mesh)
    batcher = ContinuousBatcher(sp_gen, slots=2, decode_chunk=2, block_size=4)
    try:
        streams = [batcher.submit(p) for p in prompts]
        results = [
            [int(t) for chunk in s for t in np.asarray(chunk).ravel()] for s in streams
        ]
        assert results == expected
    finally:
        batcher.close()


def test_paged_tp_preemption_recovers_token_exact():
    """Pool pressure UNDER TP: a pool sized for one worst-case request forces
    recompute preemption while the pools are model-axis-sharded — the evicted
    stream re-prefills (possibly at an exact width no bucket covers) against
    the sharded params and its total output still equals the unsharded
    sequential run. Covers the preemption/resume machinery's first composition
    with sharding (previously pinned unsharded only, tests/unit/test_continuous.py)."""
    import threading

    from unionml_tpu.serving import ContinuousBatcher

    module, params = _tiny()
    cfg = GenerationConfig(max_new_tokens=12, temperature=0.0, prompt_buckets=(8,))
    prompts = [[3, 1, 4, 1, 5], [9, 2, 6, 5, 3], [7, 1, 8]]
    expected = [list(r) for r in Generator(module, params, cfg)(prompts)]

    mesh = MeshSpec(data=1, model=2).build(jax.devices()[:2])
    tp_gen = Generator(module, params, cfg, mesh=mesh, partition_rules=llama_partition_rules())
    probe = ContinuousBatcher(tp_gen, slots=3, decode_chunk=2, block_size=4)
    min_pool = probe.max_blocks
    probe.close()
    batcher = ContinuousBatcher(
        tp_gen, slots=3, decode_chunk=2, block_size=4, pool_blocks=min_pool
    )
    try:
        results = [None] * len(prompts)

        def worker(i):
            results[i] = [
                int(t) for chunk in batcher.submit(prompts[i]) for t in np.asarray(chunk).ravel()
            ]

        threads = [threading.Thread(target=worker, args=(i,)) for i in range(len(prompts))]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=600)
        assert results == expected
        stats = batcher.stats()["kv_blocks"]
        assert stats["preemptions"] > 0  # the tight pool actually evicted someone
        assert stats["used"] == 0  # allocator balanced after all streams drained
    finally:
        batcher.close()


@pytest.mark.parametrize("seed", [11, 73])
def test_paged_tp_randomized_stress_matches_solo(seed):
    """Seeded randomized soak over the paged x TP engine: mixed prompt lengths
    and budgets through a small sharded pool (admission-wait and preemption
    prone) — every stream token-exact against its solo (prompt, budget) run."""
    from unionml_tpu.serving import ContinuousBatcher

    module, params = _tiny()
    cfg = GenerationConfig(max_new_tokens=8, temperature=0.0, prompt_buckets=(8,))
    rng = np.random.default_rng(seed)
    jobs = []
    for _ in range(8):
        plen = int(rng.integers(1, 8))
        prompt = [int(t) for t in rng.integers(1, 90, size=plen)]
        budget = int(rng.integers(1, 9))
        jobs.append((prompt, budget))

    plain = Generator(module, params, cfg)
    # greedy truncation law: a budget-b run is the first b tokens of the full run
    refs = [list(plain([p])[0])[:b] for p, b in jobs]

    mesh = MeshSpec(data=1, model=2).build(jax.devices()[:2])
    tp_gen = Generator(module, params, cfg, mesh=mesh, partition_rules=llama_partition_rules())
    batcher = ContinuousBatcher(tp_gen, slots=3, decode_chunk=2, block_size=2, pool_blocks=11)
    try:
        streams = [batcher.submit(p, max_new_tokens=b) for p, b in jobs]
        for i, (stream, ref) in enumerate(zip(streams, refs)):
            got = [int(t) for chunk in stream for t in np.asarray(chunk).ravel()]
            assert got == ref, (i, jobs[i], got, ref)
        assert batcher.stats()["kv_blocks"]["used"] == 0
    finally:
        batcher.close()


def test_everything_composes_over_tp_mesh():
    """The unit-ring capstone (int8 weights + int8 KV + paged pool + shared
    prefix + speculative + per-request grammars in one continuous engine) with
    the LAST axis added: a tensor-parallel mesh. Every concurrent stream stays
    token-exact against its solo run through the same maximal UNSHARDED engine."""
    from unionml_tpu.models import DraftSpec
    from unionml_tpu.serving import ContinuousBatcher

    module, params = _tiny()
    cs, eos = _letters_cs(r"[a-c]{2,6}")
    draft_cfg = LlamaConfig.tiny(
        vocab_size=96, dim=32, n_layers=1, n_heads=2, n_kv_heads=1, hidden_dim=64,
        dtype=jnp.float32, param_dtype=jnp.float32,
    )
    draft = Llama(draft_cfg)
    dp = draft.init(jax.random.PRNGKey(5), jnp.zeros((1, 8), jnp.int32))["params"]
    cfg = GenerationConfig(
        max_new_tokens=8, temperature=0.0, eos_id=eos, prompt_buckets=(8,),
        kv_cache_dtype="int8", constraints=cs,
        draft=DraftSpec(module=draft, params=dp, gamma=2),
    )
    prompts = [[3, 14, 15], [7, 7, 9], [1, 2]]
    gids = [1, 0, 1]

    plain = Generator(module, params, cfg, quantize="int8")
    plain_prefix = plain.cache_prefix([11, 12, 13, 14])
    solo = [
        _until_eos(plain([p], constraint=g, prefix=plain_prefix)[0], eos)
        for p, g in zip(prompts, gids)
    ]

    mesh = MeshSpec(data=1, model=2).build(jax.devices()[:2])
    tp_gen = Generator(
        module, params, cfg, mesh=mesh, partition_rules=llama_partition_rules(),
        quantize="int8",
    )
    tp_prefix = tp_gen.cache_prefix([11, 12, 13, 14])
    batcher = ContinuousBatcher(
        tp_gen, slots=2, decode_chunk=2, prefix=tp_prefix, block_size=4
    )
    try:
        streams = [batcher.submit(p, constraint=g) for p, g in zip(prompts, gids)]
        results = [
            [int(t) for chunk in s for t in np.asarray(chunk).ravel()] for s in streams
        ]
        assert results == solo
    finally:
        batcher.close()


def test_sp_prefill_resume_width_falls_back_to_dense():
    """A preemption resume's exact-width row can exceed every configured bucket;
    when its sequence-aligned width would overflow the cache, admission falls
    back to the (token-identical) dense prefill instead of failing the stream."""
    import dataclasses

    from unionml_tpu.serving import ContinuousBatcher

    module, params = _tiny()
    base = GenerationConfig(max_new_tokens=4, temperature=0.0, prompt_buckets=(8,))
    mesh = MeshSpec(data=1, sequence=4).build(jax.devices()[:4])
    sp_gen = Generator(module, params, dataclasses.replace(base, sp_prefill="ring"), mesh=mesh)
    batcher = ContinuousBatcher(sp_gen, slots=2, decode_chunk=2)
    try:
        # cache_len = 8 + 4 + 2 = 14; a 13-token resume fits exactly (13 + 1)
        # but chunk_aligned(13, 4) = 16 > 14 — the sp branch must not raise
        prompt = [3, 1, 4, 1, 5, 9, 2, 6, 5, 3, 5, 8, 9]
        assert batcher.cache_len == 14
        tok0, lengths, _, _ = batcher._prefill_row(prompt, 0, budget=1)
        expected = Generator(module, params, base)([prompt])
        assert int(np.asarray(tok0).ravel()[0]) == int(expected[0][0])
    finally:
        batcher.close()


def test_speculative_continuous_with_sp_prefill():
    """Speculative x sp x continuous: both the target's and the draft's batch-1
    admission rows prefill sequence-parallel (the draft Generator inherits the
    mesh and sp_prefill config), rounds advance through the shared spec loop,
    and each greedy stream equals the target-only solo run — the speculative
    exactness law survives ring-prefilled admission."""
    import dataclasses

    from unionml_tpu.models import DraftSpec
    from unionml_tpu.serving import ContinuousBatcher

    module, params = _tiny()
    draft_cfg = LlamaConfig.tiny(
        vocab_size=96, dim=32, n_layers=1, n_heads=2, n_kv_heads=1, hidden_dim=64,
        dtype=jnp.float32, param_dtype=jnp.float32,
    )
    draft = Llama(draft_cfg)
    dp = draft.init(jax.random.PRNGKey(5), jnp.zeros((1, 8), jnp.int32))["params"]

    base = GenerationConfig(max_new_tokens=8, temperature=0.0, prompt_buckets=(16,))
    prompts = [[3, 1, 4, 1, 5, 9, 2, 6], [7, 1, 8], [2, 8]]
    expected = [list(r) for r in Generator(module, params, base)(prompts)]

    mesh = MeshSpec(data=1, sequence=2).build(jax.devices()[:2])
    cfg = dataclasses.replace(
        base, sp_prefill="ring", draft=DraftSpec(module=draft, params=dp, gamma=3)
    )
    sp_gen = Generator(module, params, cfg, mesh=mesh)
    batcher = ContinuousBatcher(sp_gen, slots=2, decode_chunk=2)
    try:
        streams = [batcher.submit(p) for p in prompts]
        results = [
            [int(t) for chunk in s for t in np.asarray(chunk).ravel()] for s in streams
        ]
        assert results == expected
    finally:
        batcher.close()


def test_paged_kv_over_tp_mesh():
    """Paged KV x TP (the round-4 hole at continuous.py): the heads-major pools
    shard over the model axis, tables replicate, and paged decode against
    model-sharded params emits exactly the unsharded dense engine's tokens —
    including through a pool small enough to force admissions to wait."""
    from unionml_tpu.serving import ContinuousBatcher

    module, params = _tiny()
    cfg = GenerationConfig(max_new_tokens=8, temperature=0.0, prompt_buckets=(16,))
    prompts = [[3, 1, 4, 1, 5], [9, 2, 6], [7, 1], [2, 8, 1, 8]]
    expected = [list(r) for r in Generator(module, params, cfg)(prompts)]

    mesh = MeshSpec(data=1, model=4).build(jax.devices()[:4])
    sharded = Generator(module, params, cfg, mesh=mesh, partition_rules=llama_partition_rules())
    batcher = ContinuousBatcher(sharded, slots=3, decode_chunk=4, block_size=8)
    try:
        streams = [batcher.submit(p) for p in prompts]
        results = [
            [int(t) for chunk in s for t in np.asarray(chunk).ravel()] for s in streams
        ]
        assert results == expected
        assert batcher.stats()["kv_blocks"]["total"] == batcher.pool_blocks
    finally:
        batcher.close()


def test_paged_kv_with_prefix_over_tp_mesh():
    """Paged x TP x shared prefix: shared prefix pages seeded once into the
    model-sharded pool, per-request suffixes allocated privately — tokens equal
    the unsharded engine run WITH the same prefix."""
    from unionml_tpu.serving import ContinuousBatcher

    module, params = _tiny()
    cfg = GenerationConfig(max_new_tokens=6, temperature=0.0, prompt_buckets=(8,))
    prefix_tokens = [11, 12, 13, 14, 15, 16, 17, 18]
    prompts = [[3, 1, 4], [9, 2], [7, 1, 8, 2]]

    plain = Generator(module, params, cfg)
    plain_prefix = plain.cache_prefix(prefix_tokens)
    expected = [list(r) for r in plain(prompts, prefix=plain_prefix)]

    mesh = MeshSpec(data=1, model=2).build(jax.devices()[:2])
    tp_gen = Generator(module, params, cfg, mesh=mesh, partition_rules=llama_partition_rules())
    tp_prefix = tp_gen.cache_prefix(prefix_tokens)
    batcher = ContinuousBatcher(tp_gen, slots=2, decode_chunk=3, prefix=tp_prefix, block_size=4)
    try:
        streams = [batcher.submit(p) for p in prompts]
        results = [
            [int(t) for chunk in s for t in np.asarray(chunk).ravel()] for s in streams
        ]
        assert results == expected
    finally:
        batcher.close()
