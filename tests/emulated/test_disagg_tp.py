"""Disaggregated prefill/decode fleets on the emulated 8-device mesh.

The acceptance ring for docs/serving.md "Disaggregated and elastic serving":

- **tp=2 handoff exactness**: a prefill-role tp=2 replica's exported KV,
  adopted by a decode-role tp=2 replica, yields the EXACT token stream (first
  token included) of a single mixed replica — the KV crosses submeshes via
  ``jax.device_put`` and scatters into freshly allocated paged blocks;
- **dp=2×tp=2 role-split fleet** serves a mixed long-prefill + decode
  workload token-identical to a symmetric (all-mixed) fleet over the same
  mesh — disaggregation must be invisible in the output;
- **elastic resize on a dp mesh**: ``scale_to`` down drains a replica onto
  the spare-submesh pool and back up re-places params on it, with zero
  in-flight streams lost (counts asserted) and the new replica visible in
  the fleet health payload without restart.
"""

import threading

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from unionml_tpu.models import GenerationConfig, Generator, Llama, LlamaConfig, llama_partition_rules
from unionml_tpu.parallel import MeshSpec
from unionml_tpu.serving import ReplicaSet

pytestmark = pytest.mark.skipif(len(jax.devices()) < 8, reason="needs 8 emulated devices")


@pytest.fixture(scope="module")
def tiny():
    config = LlamaConfig.tiny(
        vocab_size=96, dim=64, n_layers=2, n_heads=4, n_kv_heads=2, hidden_dim=128,
        dtype=jnp.float32, param_dtype=jnp.float32,
    )
    module = Llama(config)
    params = module.init(jax.random.PRNGKey(1), jnp.zeros((1, 8), jnp.int32))["params"]
    return module, params


def _cfg(**overrides):
    kwargs = dict(max_new_tokens=8, temperature=0.0, prompt_buckets=(16,))
    kwargs.update(overrides)
    return GenerationConfig(**kwargs)


def _drain(stream):
    return [int(t) for chunk in stream for t in np.asarray(chunk).ravel()]


def _drain_concurrently(streams):
    results = [None] * len(streams)

    def worker(i):
        results[i] = _drain(streams[i])

    threads = [threading.Thread(target=worker, args=(i,)) for i in range(len(streams))]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=180)
    return results


# a mixed workload: one long prompt (the prefill-tier traffic) among short
# decode-bound ones
PROMPTS = [
    [3, 1, 4, 1, 5],
    list(range(2, 16)),  # the long prompt
    [7, 1],
    [6, 6, 6, 2],
    [9, 2, 6, 5, 3, 5],
]


def test_tp2_handoff_first_token_bit_identical(tiny):
    """The pinned cross-submesh exactness leg: prefill on one tp=2 submesh,
    decode on the other, paged KV — every token equals the single mixed
    replica run, so the handed-off KV is bit-identical to locally prefilled
    KV."""
    module, params = tiny
    cfg = _cfg()
    mesh = MeshSpec(data=2, model=2).build(devices=jax.devices()[:4])
    expected = [list(map(int, Generator(module, params, cfg)([p])[0])) for p in PROMPTS]
    fleet = ReplicaSet.build(
        module, params, cfg, mesh=mesh, partition_rules=llama_partition_rules(),
        roles={"prefill": 1, "decode": 1}, prefill_threshold=0,
        slots=2, decode_chunk=4, block_size=4,
    )
    try:
        assert fleet.roles == ["prefill", "decode"]
        for prompt, want in zip(PROMPTS, expected):
            got = _drain(fleet.submit(prompt))
            assert got == want  # element 0 is the handed-off first token
        stats = fleet.stats()
        assert stats["handoffs"]["exported"] == len(PROMPTS)
        assert stats["handoffs"]["imported"] == len(PROMPTS)
        assert stats["per_replica"][0]["decode_dispatches"] == 0
    finally:
        fleet.close()


def test_dp2tp2_role_split_matches_symmetric_fleet(tiny):
    """Role-split vs symmetric over the SAME dp=2×tp=2 mesh: identical token
    streams for a mixed long-prefill + decode workload."""
    module, params = tiny
    cfg = _cfg()

    def run(roles):
        mesh = MeshSpec(data=2, model=2).build(devices=jax.devices()[:4])
        fleet = ReplicaSet.build(
            module, params, cfg, mesh=mesh, partition_rules=llama_partition_rules(),
            roles=roles, prefill_threshold=0, slots=2, decode_chunk=4,
        )
        try:
            results = _drain_concurrently([fleet.submit(p) for p in PROMPTS])
            return results, fleet.stats()
        finally:
            fleet.close()

    symmetric, sym_stats = run(None)
    split, split_stats = run({"prefill": 1, "decode": 1})
    assert split == symmetric
    assert "handoffs" not in sym_stats  # symmetric fleets keep today's stats
    assert split_stats["roles"] == {"prefill": 1, "decode": 1, "mixed": 0}
    assert split_stats["handoffs"]["imported"] >= 1


def test_dp_mesh_scale_down_up_zero_loss(tiny):
    """Elastic resize on a dp=2 mesh mid-traffic: drain to 1 replica (the
    submesh joins the spare pool), scale back to 2 (params re-placed on it),
    with every in-flight stream completing exactly."""
    module, params = tiny
    cfg = _cfg()
    mesh = MeshSpec(data=2, model=2).build(devices=jax.devices()[:4])
    rng = np.random.default_rng(7)
    prompts = [list(map(int, rng.integers(1, 96, size=int(rng.integers(2, 10))))) for _ in range(8)]
    expected = [list(map(int, Generator(module, params, cfg)([p])[0])) for p in prompts]
    fleet = ReplicaSet.build(
        module, params, cfg, mesh=mesh, partition_rules=llama_partition_rules(),
        slots=2, decode_chunk=4,
    )
    try:
        assert fleet.replicas == 2 and fleet.spare_capacity() == 0
        results = [None] * len(prompts)

        def worker(i):
            results[i] = _drain(fleet.submit(prompts[i]))

        wave1 = [threading.Thread(target=worker, args=(i,)) for i in range(4)]
        for t in wave1:
            t.start()
        assert fleet.scale_to(1) == 1
        assert fleet.spare_capacity() == 1  # the drained submesh is reusable
        wave2 = [threading.Thread(target=worker, args=(i,)) for i in range(4, 8)]
        for t in wave2:
            t.start()
        assert fleet.scale_to(2) == 2
        for t in wave1 + wave2:
            t.join(timeout=180)
        assert results == expected  # zero dropped, zero corrupted
        # the re-added replica is live in the health payload without restart
        health = fleet.health()
        assert len(health["replicas"]) == 2
        stats = fleet.stats()
        assert stats["resize"]["scaled_up"] == 1 and stats["resize"]["scaled_down"] == 1
        assert stats["replicas"] == 2
    finally:
        fleet.close()
