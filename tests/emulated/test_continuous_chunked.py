"""Stall-free (chunked) admission token-identity on the emulated 8-device mesh.

Oracle: slicing an admission's prefill into chunks interleaved with decode must
be invisible in the tokens — every stream from a chunked engine equals both the
monolithic engine's stream and a sequential single-device ``Generator`` run
(greedy, f32), across the dp/tp matrix: a tp=2 engine, a dp=2 x tp=2
``ReplicaSet`` (knobs flow per replica through the delegation path), and the
paged preempt-resume + shared-prefix edge cases the engine must survive
mid-chunking.
"""

import threading

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from unionml_tpu.models import GenerationConfig, Generator, Llama, LlamaConfig, llama_partition_rules
from unionml_tpu.parallel import MeshSpec
from unionml_tpu.serving import ContinuousBatcher, ReplicaSet

pytestmark = pytest.mark.skipif(len(jax.devices()) < 8, reason="needs 8 emulated devices")

PROMPTS = [[3, 1, 4, 1, 5], [9, 2, 6, 5, 3, 5, 8, 9, 7, 1, 6, 2], [7, 1], [6, 6, 6, 2], [5, 5], [8]]


@pytest.fixture(scope="module")
def tiny():
    config = LlamaConfig.tiny(
        vocab_size=96, dim=64, n_layers=2, n_heads=4, n_kv_heads=2, hidden_dim=128,
        dtype=jnp.float32, param_dtype=jnp.float32,
    )
    module = Llama(config)
    params = module.init(jax.random.PRNGKey(1), jnp.zeros((1, 8), jnp.int32))["params"]
    return module, params


def _cfg(**overrides):
    base = dict(max_new_tokens=8, temperature=0.0, prompt_buckets=(16,))
    base.update(overrides)
    return GenerationConfig(**base)


def _expected(module, params, prompts, cfg=None):
    gen = Generator(module, params, cfg or _cfg())
    return [list(gen([p])[0]) for p in prompts]


def _drain(stream):
    return [int(t) for chunk in stream for t in np.asarray(chunk).ravel()]


def _drain_concurrently(streams):
    results = [None] * len(streams)

    def worker(i):
        results[i] = _drain(streams[i])

    threads = [threading.Thread(target=worker, args=(i,)) for i in range(len(streams))]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=180)
    return results


def test_tp2_chunked_admission_matches_monolithic_and_sequential(tiny):
    """tp=2 leg of the matrix: chunked admission over a model-sharded engine
    emits EXACTLY the single-device sequential run's tokens — which IS the
    monolithic engine's output (the existing TP continuous tests pin
    monolithic == sequential), so slicing composes with TP collectives."""
    module, params = tiny
    expected = _expected(module, params, PROMPTS)
    mesh = MeshSpec(data=1, model=2).build(devices=jax.devices()[:2])
    gen = Generator(module, params, _cfg(), mesh=mesh, partition_rules=llama_partition_rules())
    batcher = ContinuousBatcher(gen, slots=3, decode_chunk=4, admit_chunk=4)
    try:
        streams = [batcher.submit(p) for p in PROMPTS]
        assert _drain_concurrently(streams) == expected
        stats = batcher.stats()
        assert stats["prefill"]["mode"] == "chunked" and stats["prefill"]["chunks"] > 0
    finally:
        batcher.close()


def test_dp2_tp2_replicaset_chunked_admission_token_identical(tiny):
    """dp=2 x tp=2 leg: the ContinuousBatcher delegation path carries the
    stall-free knobs to every replica engine, and the fleet's streams still
    equal the sequential single-device run."""
    module, params = tiny
    expected = _expected(module, params, PROMPTS)
    mesh = MeshSpec(data=2, model=2).build(devices=jax.devices()[:4])
    gen = Generator(module, params, _cfg(), mesh=mesh, partition_rules=llama_partition_rules())
    engine = ContinuousBatcher(gen, slots=2, decode_chunk=4, admit_chunk=4, prefill_budget=4)
    try:
        assert isinstance(engine, ReplicaSet) and engine.replicas == 2
        for batcher in engine.batchers:
            assert batcher.admit_chunk == 4 and batcher.prefill_budget == 4
        streams = [engine.submit(p) for p in PROMPTS]
        assert _drain_concurrently(streams) == expected
        stats = engine.stats()
        assert stats["prefill_chunks"] > 0  # fleet-wide counter aggregated
        for entry in stats["per_replica"]:
            assert {"ttft_ms", "tbt_ms", "prefill"} <= set(entry)
    finally:
        engine.close()


def test_chunked_paged_preempt_resume_and_shared_prefix(tiny):
    """The two admission edge cases the chunked engine must survive, in one
    paged ring: a shared prefix (chunks start past the pasted prefix rows)
    and pool-pressure preemption (the resume's exact width falls back to a
    monolithic prefill when its aligned width would overflow) — both
    token-identical to the sequential dense run."""
    module, params = tiny
    cfg = _cfg(max_new_tokens=12, prompt_buckets=(8, 16))
    prefix = [7, 7, 3, 9, 1, 2, 5, 11]
    suffixes = [[3, 1, 4], [9, 2, 6, 5], [8, 4, 4, 1, 2, 6]]
    expected = _expected(module, params, [prefix + s for s in suffixes], cfg)

    gen = Generator(module, params, cfg)
    batcher = ContinuousBatcher(
        gen, slots=2, decode_chunk=3, prefix=gen.cache_prefix(prefix),
        block_size=8, admit_chunk=4,
    )
    try:
        assert len(batcher._shared_prefix_blocks) == 1  # 8 // 8: pages shared
        results = [_drain(batcher.submit(s)) for s in suffixes]
        assert results == expected
        assert batcher.stats()["prefill"]["chunks"] > 0
    finally:
        batcher.close()

    # preemption leg: pool too small for two long residents; the evicted
    # stream resumes (prompt + echo outgrows the bucket set) and must stay
    # exact under chunked admission
    cfg = _cfg(max_new_tokens=16)
    long_prompts = [[3, 1, 4, 1, 5, 9, 2, 6, 5, 3, 5, 8, 9, 7], [2, 7, 1, 8, 2, 8, 1, 8, 2, 8, 4, 5, 9, 4]]
    expected = _expected(module, params, long_prompts, cfg)
    gen = Generator(module, params, cfg)
    probe = ContinuousBatcher(gen, slots=2, decode_chunk=8, block_size=8, admit_chunk=8)
    pool = 2 * probe._blocks_initial(long_prompts[0], cfg.max_new_tokens)
    probe.close()
    batcher = ContinuousBatcher(
        gen, slots=2, decode_chunk=8, block_size=8, pool_blocks=pool, admit_chunk=8
    )
    try:
        streams = [batcher.submit(p) for p in long_prompts]
        assert _drain_concurrently(streams) == expected
        assert batcher.stats()["kv_blocks"]["preemptions"] > 0
    finally:
        batcher.close()
