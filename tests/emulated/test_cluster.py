"""Multi-host serving fleet: REAL worker subprocesses forming one
multi-process CPU JAX runtime (the ``job_runner`` emulation pattern, pointed
at serving instead of training).

Each worker joins ``jax.distributed`` through the shared bootstrap
(``unionml_tpu/distributed.py``), agrees on the fleet config over
``multihost_utils``, builds its ReplicaSet over ITS host-local slice of a
hybrid ICI/DCN mesh (DCN on the replica axis, ICI on the model axis — the
T5X partitioning shape), and serves a loopback control server. The test
process is the COORDINATOR: pure control-plane HTTP, deliberately outside
the jax runtime — a worker crash breaks a TCP connection, never a
collective.

Pinned here (the ISSUE 13 acceptance criteria):

- a 2-host × tp=2 fleet serves token-identical to the single-process
  dp=2×tp=2 reference;
- a cross-host prefill→decode handoff (block-native pages over the wire) is
  bit-identical, transfer latency captured;
- fleet-global prefix routing lands turn 2 on the warm host;
- killing a worker mid-fleet sheds nothing: the coordinator routes around
  the dead host;
- (ISSUE 15) a SIGKILLed worker REJOINS: a replacement process announces
  into the same rendezvous dir with a fresh epoch, the reconciliation loop
  walks it through probation (probes + warmup) back to live, and the
  post-rejoin fleet serves token-identical to the no-fault reference.
"""

import json
import os
import signal
import socket
import subprocess
import sys
import textwrap
import time
from pathlib import Path

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from unionml_tpu.serving.cluster import connect_fleet

pytestmark = pytest.mark.skipif(len(jax.devices()) < 8, reason="needs 8 emulated devices")

REPO = Path(__file__).resolve().parents[2]

#: the fleet app every worker (and the in-parent reference) builds from —
#: fixed seeds, so every process derives bit-identical weights
FLEET_APP = textwrap.dedent(
    """
    import jax
    import jax.numpy as jnp

    from unionml_tpu.models import (
        GenerationConfig, Generator, Llama, LlamaConfig, llama_partition_rules,
    )
    from unionml_tpu.parallel import MeshSpec
    from unionml_tpu.serving import ReplicaSet


    def tiny():
        config = LlamaConfig.tiny(
            vocab_size=96, dim=64, n_layers=2, n_heads=4, n_kv_heads=2, hidden_dim=128,
            dtype=jnp.float32, param_dtype=jnp.float32,
        )
        module = Llama(config)
        params = module.init(jax.random.PRNGKey(1), jnp.zeros((1, 8), jnp.int32))["params"]
        return module, params


    def gen_config(max_new_tokens=8):
        return GenerationConfig(
            max_new_tokens=max_new_tokens, temperature=0.0, prompt_buckets=(16,)
        )


    def build_engine(prefix_cache=False, replicas=None):
        # the hybrid ICI/DCN mesh over the WHOLE runtime: DCN carries the
        # replica axes (one batch slice per host; `data` takes any leftover
        # within-host extent), ICI the model axis; the process-aware
        # ReplicaSet keeps only this host's submeshes
        module, params = tiny()
        mesh = MeshSpec(dcn_data=jax.process_count(), model=2).build_hybrid()
        return ReplicaSet.build(
            module, params, gen_config(),
            mesh=mesh, partition_rules=llama_partition_rules(), replicas=replicas,
            slots=2, decode_chunk=4, block_size=8, pool_blocks=64,
            prefix_cache=prefix_cache,
        )
    """
)

PROMPTS = [[3, 1, 4, 1, 5], [9, 2, 6, 5, 3, 5, 8, 9], [7, 1], [6, 6, 6, 2]]


def _free_port() -> int:
    with socket.socket() as sock:
        sock.bind(("127.0.0.1", 0))
        return sock.getsockname()[1]


def _drain(stream):
    return [int(t) for chunk in stream for t in np.asarray(chunk).ravel()]


class _Fleet:
    """Spawn N worker subprocesses and connect a coordinator to them."""

    def __init__(self, tmp_path, *, n_workers=2, devices_per_worker=2,
                 kwargs=None, roles=None):
        (tmp_path / "fleet_app.py").write_text(FLEET_APP)
        self.fleet_dir = tmp_path / "fleet"
        port = _free_port()
        self.procs = []
        self.logs = []
        for pid in range(n_workers):
            spec = {
                "builder": "fleet_app:build_engine",
                "kwargs": kwargs or {},
                "fleet_dir": str(self.fleet_dir),
                "role": (roles or ["mixed"] * n_workers)[pid],
            }
            spec_path = tmp_path / f"spec{pid}.json"
            spec_path.write_text(json.dumps(spec))
            env = os.environ.copy()
            env.update({
                "JAX_PLATFORMS": "cpu",
                "XLA_FLAGS": f"--xla_force_host_platform_device_count={devices_per_worker}",
                "UNIONML_TPU_COORDINATOR": f"127.0.0.1:{port}",
                "UNIONML_TPU_NUM_PROCESSES": str(n_workers),
                "UNIONML_TPU_PROCESS_ID": str(pid),
                "PYTHONPATH": os.pathsep.join([str(tmp_path), str(REPO)]),
            })
            log = open(tmp_path / f"worker{pid}.log", "w")
            self.logs.append(log)
            self.procs.append(subprocess.Popen(
                [sys.executable, "-m", "unionml_tpu.serving.cluster", str(spec_path)],
                env=env, stdout=log, stderr=subprocess.STDOUT, cwd=tmp_path,
            ))
        self.tmp_path = tmp_path
        self.n_workers = n_workers

    def connect(self, **kwargs):
        # wait for every announcement ourselves so a worker that CRASHES at
        # build time fails the test immediately with its log, not after the
        # rendezvous timeout
        deadline = time.monotonic() + 300.0
        while time.monotonic() < deadline:
            for pid, proc in enumerate(self.procs):
                if proc.poll() is not None:
                    raise RuntimeError(
                        f"worker {pid} exited rc={proc.returncode} before announcing:\n"
                        + self.tail_logs()
                    )
            if self.fleet_dir.exists() and len(list(self.fleet_dir.glob("host-*.json"))) >= self.n_workers:
                break
            time.sleep(0.2)
        else:
            raise TimeoutError("fleet rendezvous timed out; worker logs:\n" + self.tail_logs())
        return connect_fleet(
            self.fleet_dir, num_hosts=self.n_workers, timeout_s=60.0, **kwargs
        )

    def tail_logs(self) -> str:
        out = []
        for pid in range(self.n_workers):
            path = self.tmp_path / f"worker{pid}.log"
            if path.exists():
                out.append(f"--- worker {pid} ---\n" + path.read_text()[-2000:])
        return "\n".join(out)

    def kill(self, pid: int) -> None:
        self.procs[pid].kill()
        self.procs[pid].wait(timeout=30)

    def spawn_replacement(self, pid: int) -> None:
        """Start a REPLACEMENT worker for a SIGKILLed process id: it joins
        the control plane only (no jax.distributed — the control plane is
        out-of-band by design, so a replacement host never has to rejoin a
        dead collective), builds the same engine single-process, and
        announces into the same rendezvous dir with a fresh epoch."""
        spec = {
            "builder": "fleet_app:build_engine",
            "kwargs": {},
            "fleet_dir": str(self.fleet_dir),
            "role": "mixed",
        }
        spec_path = self.tmp_path / f"spec-replacement{pid}.json"
        spec_path.write_text(json.dumps(spec))
        env = os.environ.copy()
        env.pop("UNIONML_TPU_COORDINATOR", None)
        env.pop("UNIONML_TPU_NUM_PROCESSES", None)
        env.update({
            "JAX_PLATFORMS": "cpu",
            "XLA_FLAGS": "--xla_force_host_platform_device_count=2",
            "UNIONML_TPU_PROCESS_ID": str(pid),
            "PYTHONPATH": os.pathsep.join([str(self.tmp_path), str(REPO)]),
        })
        log = open(self.tmp_path / f"worker{pid}-replacement.log", "w")
        self.logs.append(log)
        self.procs.append(subprocess.Popen(
            [sys.executable, "-m", "unionml_tpu.serving.cluster", str(spec_path)],
            env=env, stdout=log, stderr=subprocess.STDOUT, cwd=self.tmp_path,
        ))

    def close(self) -> None:
        for proc in self.procs:
            if proc.poll() is None:
                proc.send_signal(signal.SIGTERM)
        deadline = time.monotonic() + 30
        for proc in self.procs:
            try:
                proc.wait(timeout=max(deadline - time.monotonic(), 1))
            except subprocess.TimeoutExpired:
                proc.kill()
        for log in self.logs:
            log.close()


@pytest.fixture()
def reference(tmp_path_factory):
    """The single-process dp=2×tp=2 oracle, built once from the same app
    source in THIS process (8 emulated devices; the fleet uses 4 of them
    spread over 2 workers)."""
    import importlib

    app_dir = tmp_path_factory.mktemp("refapp")
    (app_dir / "ref_fleet_app.py").write_text(FLEET_APP.replace("fleet_app", "ref_fleet_app"))
    sys.path.insert(0, str(app_dir))
    try:
        ref_app = importlib.import_module("ref_fleet_app")
        yield ref_app
    finally:
        sys.path.remove(str(app_dir))
        sys.modules.pop("ref_fleet_app", None)


_REF_GEN = {}


def _reference_tokens(ref_app, prompts, max_new_tokens=8):
    # one Generator (and one compile set) per budget for the whole module —
    # the 1-core tier-1 budget is the scarce resource here
    from unionml_tpu.models import Generator

    gen = _REF_GEN.get(max_new_tokens)
    if gen is None:
        module, params = ref_app.tiny()
        gen = _REF_GEN[max_new_tokens] = Generator(
            module, params, ref_app.gen_config(max_new_tokens)
        )
    return [list(map(int, gen([p])[0])) for p in prompts]


def _reference_fleet_tokens(ref_app, prompts):
    """The SINGLE-PROCESS dp=2×tp=2 ReplicaSet reference the emulated fleet
    must match token-for-token."""
    from unionml_tpu.models import Generator, llama_partition_rules
    from unionml_tpu.parallel import MeshSpec
    from unionml_tpu.serving import ReplicaSet

    module, params = ref_app.tiny()
    mesh = MeshSpec(data=2, model=2).build(devices=jax.devices()[:4])
    fleet = ReplicaSet.build(
        module, params, ref_app.gen_config(),
        mesh=mesh, partition_rules=llama_partition_rules(),
        slots=2, decode_chunk=4, block_size=8, pool_blocks=64,
    )
    try:
        return [_drain(fleet.submit(p)) for p in prompts]
    finally:
        fleet.close()


def test_two_host_fleet_token_identity_prefix_routing_and_worker_death(
    tmp_path, reference
):
    """The tier-1 pin of the whole subsystem, one fleet session: identity vs
    the single-process reference, fleet-global prefix routing, and clean
    degradation when a worker dies."""
    fleet = _Fleet(tmp_path, n_workers=2, kwargs={"prefix_cache": True})
    try:
        coordinator = fleet.connect()
        # both workers joined ONE jax.distributed runtime and built from the
        # hybrid mesh: the log line the bootstrap contract pins
        time.sleep(0)  # (logs already flushed by announce time)
        logs = fleet.tail_logs()
        assert "joined jax.distributed runtime: process 0/2, global devices 4 (2 local)" in logs
        assert "this host owns replica submeshes" in logs

        # --- token identity: fleet streams == single-process dp=2xtp=2 fleet
        # == sequential oracle
        got = [_drain(coordinator.submit(p)) for p in PROMPTS]
        oracle = _reference_tokens(reference, PROMPTS)
        assert got == oracle
        assert _reference_fleet_tokens(reference, PROMPTS) == oracle
        stats = coordinator.stats()
        assert stats["live_hosts"] == 2
        assert stats["replicas"] == 2  # one tp=2 replica per host
        assert sum(coordinator._scheduler.stats()["submitted"]) == len(PROMPTS)

        # --- fleet-global prefix routing: warm host 1 directly with a FRESH
        # conversation (none of PROMPTS — those already warmed host 0 through
        # decode-side insertion), then the coordinator's turn 2 must land on
        # host 1 (actual radix probe, not LRU)
        turn1 = [5, 5, 4, 4, 3, 3, 2, 2]
        reply = _drain(coordinator.hosts[1].submit(turn1))
        turn2 = list(turn1) + reply + [11, 12]
        # decode-side radix insertion publishes at slot release on the engine
        # thread, a beat after the consumer sees the last token — wait for the
        # probe to see the warm run before routing on it
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline and coordinator.hosts[1].probe(turn2)["cached"] == 0:
            time.sleep(0.05)
        assert coordinator.hosts[1].probe(turn2)["cached"] > 0
        probes = coordinator._probe_all(coordinator._live(), turn2)
        warm = _drain(coordinator.submit(turn2))
        submitted = coordinator._scheduler.stats()["submitted"]
        assert submitted[1] >= 1, (submitted, probes, [h.alive for h in coordinator.hosts])
        host1_stats = coordinator.hosts[1].stats()
        assert host1_stats["prefix_cache"]["hits"] >= 1
        assert warm == _reference_tokens(reference, [turn2])[0]

        # --- worker death MID-STREAM: submit the whole prompt set, SIGKILL
        # host 1's process while streams are in flight, then drain. The fault
        # contract: a stream the dead host had accepted but not started
        # emitting is retried transparently on host 0 (token-identical); one
        # that had already emitted raises the clean 503-shaped
        # StreamInterrupted — and nothing hangs
        from unionml_tpu.serving.cluster import StreamInterrupted

        streams = [coordinator.submit(p) for p in PROMPTS]
        fleet.kill(1)
        clean_errors = 0
        for prompt, stream, want in zip(PROMPTS, streams, oracle):
            try:
                assert _drain(stream) == want
            except StreamInterrupted:
                clean_errors += 1  # emitted-then-died: clean, never silent
        assert clean_errors <= len(PROMPTS)  # zero accepted streams LOST
        # every subsequent submission sheds nothing: host 0 serves alone
        got = [_drain(coordinator.submit(p)) for p in PROMPTS]
        assert got == oracle
        assert coordinator.hosts[1].alive is False
        assert coordinator.stats()["live_hosts"] == 1
        census = coordinator.host_census()
        assert census[1]["alive"] is False
        assert census[1]["state"] in ("suspect", "dead")

        # --- kill → REJOIN through probation (the ISSUE 15 acceptance pin):
        # a replacement worker process announces into the same rendezvous dir
        # (fresh epoch, new port — same host id) and the coordinator's
        # reconciliation loop walks it suspect/dead → probation → live
        fleet.spawn_replacement(1)
        deadline = time.monotonic() + 240
        while time.monotonic() < deadline and not coordinator.hosts[1].alive:
            time.sleep(0.5)
        assert coordinator.hosts[1].alive, (
            f"replacement never rejoined: state={coordinator.hosts[1].state}\n"
            + fleet.tail_logs()
        )
        assert coordinator.hosts[1].rejoins >= 1
        stats = coordinator.stats()
        assert stats["live_hosts"] == 2
        assert stats["fleet"]["host_rejoins"] >= 1
        assert stats["fleet"]["host_suspects"] >= 1
        assert stats["fleet"]["recovery_ms"]["window"] >= 1
        assert coordinator.host_census()[1]["state"] == "live"
        # the post-rejoin fleet serves token-identical to the no-fault
        # reference, and the rejoined host answers its routing probe
        got = [_drain(coordinator.submit(p)) for p in PROMPTS]
        assert got == oracle
        assert 1 in coordinator._probe_all(coordinator._live(), PROMPTS[0])
    finally:
        fleet.close()


def test_cross_host_handoff_bit_identical(tmp_path, reference):
    """Host-level disaggregation across PROCESSES: prefill on host 0, KV
    pages over the wire, decode on host 1 — token-identical to the oracle,
    with the transfer latency captured."""
    fleet = _Fleet(tmp_path, n_workers=2, roles=["prefill", "decode"])
    try:
        coordinator = fleet.connect(prefill_threshold=1)
        assert coordinator.roles == ["prefill", "decode"]
        got = [_drain(coordinator.submit(p)) for p in PROMPTS]
        assert got == _reference_tokens(reference, PROMPTS)
        stats = coordinator.stats()
        assert stats["handoffs_cross_host"] == len(PROMPTS)
        assert stats["handoff_transfer_ms"]["window"] == len(PROMPTS)
        # the decode host really imported (and the prefill host exported)
        host_stats = [entry["stats"] for entry in stats["hosts"]]
        assert sum(
            (replica.get("handoff") or {}).get("exported", 0)
            for replica in host_stats[0]["per_replica"]
        ) == len(PROMPTS)
        assert sum(
            (replica.get("handoff") or {}).get("imported", 0)
            for replica in host_stats[1]["per_replica"]
        ) == len(PROMPTS)
    finally:
        fleet.close()


@pytest.mark.slow
def test_cross_host_scale_to_zero_stream_loss(tmp_path, reference):
    """The deep leg: resize the live 2-host fleet (1 → 2 replicas per host
    and back) while streams are in flight — zero loss, and the per-host
    ReplicaSets report the resize."""
    import threading

    fleet = _Fleet(tmp_path, n_workers=2, devices_per_worker=4, kwargs={"replicas": 1})
    try:
        coordinator = fleet.connect()
        results = {}

        def consume(index, stream):
            out = []
            for chunk in stream:
                out.extend(int(t) for t in np.asarray(chunk).ravel())
                time.sleep(0.01)
            results[index] = out

        streams = [coordinator.submit(p) for p in PROMPTS]
        threads = [
            threading.Thread(target=consume, args=(i, s)) for i, s in enumerate(streams)
        ]
        for thread in threads:
            thread.start()
        assert coordinator.scale_to(4) == 4
        assert coordinator.scale_to(2) == 2
        for thread in threads:
            thread.join(timeout=300)
        assert [results[i] for i in range(len(PROMPTS))] == _reference_tokens(reference, PROMPTS)
    finally:
        fleet.close()
