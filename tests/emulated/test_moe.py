"""MoE / expert parallelism on the emulated 8-device CPU mesh.

Oracles: (1) dispatch/combine tensors must reproduce a per-token loop over the
router's top-k choices when capacity is ample; (2) the MoE layer must equal a
directly-indexed per-token expert mixture; (3) the expert-parallel train step must
run sharded over an ``expert`` mesh axis and move the params.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
import optax
from flax.training import train_state

from unionml_tpu.models import MoEConfig, MoELayer, MoETransformer, moe_lm_loss, moe_partition_rules, top_k_dispatch
from unionml_tpu.parallel import MeshSpec, shard_pytree

pytestmark = pytest.mark.skipif(len(jax.devices()) < 8, reason="needs 8 emulated devices")


def test_top_k_dispatch_matches_loop_oracle():
    rng = np.random.default_rng(0)
    n_tokens, n_experts, k, capacity = 32, 4, 2, 32  # ample capacity: nothing dropped
    probs = jax.nn.softmax(jnp.asarray(rng.normal(size=(n_tokens, n_experts))), -1)
    dispatch, combine, aux = top_k_dispatch(probs, k, capacity)

    probs_np = np.asarray(probs)
    for token in range(n_tokens):
        top = np.argsort(-probs_np[token])[:k]
        gates = probs_np[token][top]
        gates = gates / gates.sum()
        for expert in range(n_experts):
            d_row = np.asarray(dispatch[token, expert])
            c_row = np.asarray(combine[token, expert])
            if expert in top:
                assert d_row.sum() == pytest.approx(1.0), (token, expert)  # one capacity slot
                np.testing.assert_allclose(c_row.sum(), gates[list(top).index(expert)], rtol=1e-5)
            else:
                assert d_row.sum() == 0.0 and c_row.sum() == 0.0
    assert float(aux) > 0


def test_top_k_dispatch_drops_overflow():
    # all tokens pick expert 0 -> only `capacity` of them may land
    probs = jnp.tile(jnp.asarray([[0.97, 0.01, 0.01, 0.01]]), (16, 1))
    dispatch, _, _ = top_k_dispatch(probs, 1, 4)
    assert float(dispatch[:, 0].sum()) == 4.0  # capacity slots filled, 12 dropped
    for slot in range(4):
        assert float(dispatch[:, 0, slot].sum()) == 1.0  # each slot used exactly once


def test_moe_layer_matches_per_token_oracle():
    """Ample capacity: layer output == directly applying each token's top-k experts."""
    config = dict(n_experts=4, hidden_dim=32, k=2, capacity_factor=8.0, dtype=jnp.float32, param_dtype=jnp.float32)
    layer = MoELayer(**config)
    x = jax.random.normal(jax.random.PRNGKey(0), (2, 8, 16))
    params = layer.init(jax.random.PRNGKey(1), x)["params"]
    out, _ = layer.apply({"params": params}, x, mutable=["losses"])

    # oracle: run every expert densely on every token, combine by renormalized top-k gates
    tokens = np.asarray(x.reshape(-1, 16))
    router_w = np.asarray(params["router"]["kernel"])
    logits = tokens @ router_w
    probs = np.asarray(jax.nn.softmax(jnp.asarray(logits), -1))

    from unionml_tpu.models.layers import MLP

    expert_params = params["experts"]
    per_expert = []
    for e in range(4):
        p_e = jax.tree_util.tree_map(lambda leaf: leaf[e], expert_params)
        per_expert.append(np.asarray(MLP(hidden_dim=32, gated=True, dtype=jnp.float32, param_dtype=jnp.float32).apply({"params": p_e}, jnp.asarray(tokens))))

    expected = np.zeros_like(tokens)
    for t in range(tokens.shape[0]):
        top = np.argsort(-probs[t])[:2]
        gates = probs[t][top] / probs[t][top].sum()
        for gate, e in zip(gates, top):
            expected[t] += gate * per_expert[e][t]
    np.testing.assert_allclose(np.asarray(out).reshape(-1, 16), expected, atol=1e-4)


def test_moe_transformer_expert_parallel_train_step():
    """One train step with experts sharded over the expert axis on a data x expert mesh."""
    mesh = MeshSpec(data=2, expert=4).build()
    config = MoEConfig.tiny(n_experts=4, dtype=jnp.float32, param_dtype=jnp.float32)
    module = MoETransformer(config)
    tokens = jax.random.randint(jax.random.PRNGKey(0), (8, 16), 0, config.vocab_size)
    params = module.init(jax.random.PRNGKey(1), tokens)["params"]

    rules = moe_partition_rules()
    from jax.sharding import PartitionSpec as P

    assert rules.spec_for("layer_0/moe/experts/wi/kernel") == P("expert", "fsdp", "model")
    shardings = rules.shardings(params, mesh)
    params = shard_pytree(params, shardings)
    expert_leaf = params["layer_0"]["moe"]["experts"]["wi"]["kernel"]
    assert "expert" in expert_leaf.sharding.spec

    state = train_state.TrainState.create(apply_fn=None, params=params, tx=optax.adam(1e-3))

    @jax.jit
    def step(state, batch):
        loss, grads = jax.value_and_grad(lambda p: moe_lm_loss(module, p, batch))(state.params)
        return state.apply_gradients(grads=grads), loss

    with mesh:
        state2, loss = step(state, tokens)
        state2, loss2 = step(state2, tokens)
    assert np.isfinite(float(loss)) and np.isfinite(float(loss2))
    assert float(loss2) < float(loss)  # optimizing
    diff = jax.tree_util.tree_map(lambda a, b: float(jnp.abs(a - b).max()), state.params, state2.params)
    assert max(jax.tree_util.tree_leaves(diff)) > 0


def test_moe_aux_loss_encourages_balance():
    """The aux loss is minimal (== 1.0 for top-1 uniform) when routing is uniform."""
    n = 64
    uniform = jnp.full((n, 4), 0.25)
    _, _, aux_uniform = top_k_dispatch(uniform, 1, 64)
    skewed = jnp.tile(jnp.asarray([[0.9, 0.05, 0.03, 0.02]]), (n, 1))
    _, _, aux_skewed = top_k_dispatch(skewed, 1, 64)
    assert float(aux_skewed) > float(aux_uniform)


def test_moe_sharding_constraint_engages_under_mesh():
    """Regression: the expert-dim sharding constraint must appear in the lowered
    program when tracing under a mesh with an expert axis (it silently no-ops
    without a visible mesh, which would turn EP into full replication)."""
    from jax.sharding import PartitionSpec as P

    from unionml_tpu.models.moe import _constrain

    mesh = MeshSpec(data=2, expert=4).build()
    with mesh:
        txt = jax.jit(lambda x: _constrain(x, P("expert", None, None)) * 2).lower(
            jnp.zeros((4, 8, 16))
        ).as_text()
    assert "sharding" in txt.lower(), "expert sharding constraint did not lower"
