"""Quantized serving token-identity on the emulated 8-device mesh.

Oracle: int8 weights + an int8 paged KV pool must be invisible to the serving
topology — a tp=2 engine's warm (radix-cache-hit) streams equal its cold
first-visit streams AND a single-device quantized sequential ``Generator`` run
(greedy, f32 compute), the scale planes riding through the sharded
gather/scatter. The dp=2 x tp=2 leg pins the delegation path the replica layer
used to reject: ``ContinuousBatcher`` over a quantized dp-mesh Generator
transparently builds a ``ReplicaSet`` whose per-replica re-quantization is an
exact round trip.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from unionml_tpu.models import GenerationConfig, Generator, Llama, LlamaConfig, llama_partition_rules
from unionml_tpu.ops.quant import QuantizedTensor
from unionml_tpu.parallel import MeshSpec
from unionml_tpu.serving import ContinuousBatcher, ReplicaSet

pytestmark = pytest.mark.skipif(len(jax.devices()) < 8, reason="needs 8 emulated devices")

SYSTEM = [7, 7, 3, 9, 1, 2, 5, 11, 4, 8, 6, 10, 12, 3, 2, 9, 5, 1]  # 18 shared tokens
PROMPTS = [SYSTEM + tail for tail in ([30, 31], [30, 32, 33], [40], [30, 31, 35, 36])]


@pytest.fixture(scope="module")
def tiny():
    # hidden_dim 1024: the MLP kernels cross quantize_params' min_size, so
    # quantize="int8" genuinely serves int8 weights on every leg below
    config = LlamaConfig.tiny(
        vocab_size=96, dim=64, n_layers=2, n_heads=4, n_kv_heads=2, hidden_dim=1024,
        dtype=jnp.float32, param_dtype=jnp.float32,
    )
    module = Llama(config)
    params = module.init(jax.random.PRNGKey(1), jnp.zeros((1, 8), jnp.int32))["params"]
    return module, params


def _cfg(**overrides):
    base = dict(
        max_new_tokens=8, temperature=0.0, prompt_buckets=(32,), kv_cache_dtype="int8"
    )
    base.update(overrides)
    return GenerationConfig(**base)


def _expected(module, params, prompts, cfg=None):
    gen = Generator(module, params, cfg or _cfg(), quantize="int8")
    return [list(gen([p])[0]) for p in prompts]


def _drain(stream):
    return [int(t) for chunk in stream for t in np.asarray(chunk).ravel()]


def _has_quantized_leaf(tree) -> bool:
    return any(
        isinstance(leaf, QuantizedTensor)
        for leaf in jax.tree_util.tree_leaves(tree, is_leaf=lambda x: isinstance(x, QuantizedTensor))
    )


def test_tp2_int8_pool_warm_equals_cold_and_sequential(tiny):
    """tp=2 leg: int8 pools shard heads-major over the model axis with their
    f32 scale planes alongside; warm streams — gathered from cached int8
    blocks, chunk-prefilled from the first uncached token — equal the cold
    stream and the single-device quantized sequential run exactly."""
    module, params = tiny
    expected = _expected(module, params, PROMPTS)
    mesh = MeshSpec(data=1, model=2).build(devices=jax.devices()[:2])
    gen = Generator(
        module, params, _cfg(), mesh=mesh,
        partition_rules=llama_partition_rules(), quantize="int8",
    )
    assert _has_quantized_leaf(gen.params)
    batcher = ContinuousBatcher(
        gen, slots=2, decode_chunk=4, block_size=8, admit_chunk=8, prefix_cache=True
    )
    try:
        cold = _drain(batcher.submit(PROMPTS[0]))  # publishes SYSTEM's int8 blocks
        assert cold == expected[0]
        warm = [_drain(batcher.submit(p)) for p in PROMPTS[1:]]
        assert warm == expected[1:]
        stats = batcher.stats()
        assert stats["prefix_cache"]["hits"] == len(PROMPTS) - 1
        assert stats["prefix_cache"]["tokens_avoided"] > 0
        assert stats["kv_blocks"]["kv_dtype"] == "int8"
        pool = batcher._carry[0]
        assert pool[0]["k"].dtype == jnp.int8 and pool[0]["k_scale"].dtype == jnp.float32
    finally:
        batcher.close()


@pytest.mark.slow  # ~6s; tier-1 keeps the tp=2 identity leg above — this leg
# adds the dp-mesh delegation composition over the same from_generator
# dequantize-requantize round trip the (slow) unit replication test pins
def test_dp2_tp2_quantized_delegation_replicates_exactly(tiny):
    """dp=2 x tp=2 leg: ContinuousBatcher over a PRE-QUANTIZED dp-mesh
    Generator delegates to a ReplicaSet (the path replicas.py:396 used to
    reject) — each replica dequantizes + re-quantizes its own placement, an
    exact round trip, and the fleet's streams equal the sequential quantized
    run with the prefix cache steering warm traffic."""
    module, params = tiny
    expected = _expected(module, params, PROMPTS)
    mesh = MeshSpec(data=2, model=2).build(devices=jax.devices()[:4])
    gen = Generator(
        module, params, _cfg(), mesh=mesh,
        partition_rules=llama_partition_rules(), quantize="int8",
    )
    engine = ContinuousBatcher(
        gen, slots=2, decode_chunk=4, block_size=8, admit_chunk=8, prefix_cache=True
    )
    try:
        assert isinstance(engine, ReplicaSet) and engine.replicas == 2
        for batcher in engine.batchers:
            assert batcher.gen.quantize == "int8"
            assert batcher.gen.config.kv_cache_dtype == "int8"
            assert _has_quantized_leaf(batcher.gen.params)
        results = [_drain(engine.submit(p)) for p in PROMPTS]
        assert results == expected
        stats = engine.stats()
        assert stats["prefix_cache"]["hits"] >= len(PROMPTS) - 1
        assert stats["prefix_cache"]["cached_bytes"] > 0
    finally:
        engine.close()
