"""PrefetchIterator contract: ordering, remainder handling, background production."""

import numpy as np
import pytest

import jax

from unionml_tpu.data.pipeline import PrefetchIterator
from unionml_tpu.parallel import MeshSpec
from unionml_tpu.parallel.sharding import batch_sharding

pytestmark = pytest.mark.skipif(len(jax.devices()) < 8, reason="needs 8 emulated devices")


@pytest.mark.parametrize("prefetch", [0, 1, 3])
def test_prefetch_preserves_order_and_content(prefetch):
    X = np.arange(40, dtype=np.float32).reshape(20, 2)
    y = np.arange(20, dtype=np.int32)
    it = PrefetchIterator([X, y], batch_size=4, shuffle=False, prefetch=prefetch)
    batches = list(it)
    assert len(batches) == len(it) == 5
    got_y = np.concatenate([np.asarray(b[1]) for b in batches])
    np.testing.assert_array_equal(got_y, y)
    got_X = np.concatenate([np.asarray(b[0]) for b in batches])
    np.testing.assert_array_equal(got_X, X)


def test_prefetch_sharded_placement_and_partial_batch():
    mesh = MeshSpec(data=-1).build()
    sharding = batch_sharding(mesh)
    X = np.arange(22 * 8, dtype=np.float32).reshape(22, 8)
    it = PrefetchIterator([X], batch_size=8, sharding=sharding, drop_remainder=False, prefetch=2)
    batches = list(it)
    assert [b[0].shape[0] for b in batches] == [8, 8, 6]
    assert batches[0][0].sharding.is_equivalent_to(sharding, 2)  # full batches: data-sharded
    got = np.concatenate([np.asarray(b[0]) for b in batches])
    np.testing.assert_array_equal(got, X)


def test_prefetch_shuffle_is_seeded_and_epochwise():
    y = np.arange(64, dtype=np.int32)
    a = [np.asarray(b[0]) for b in PrefetchIterator([y], batch_size=16, shuffle=True, seed=3, epochs=2)]
    b = [np.asarray(x[0]) for x in PrefetchIterator([y], batch_size=16, shuffle=True, seed=3, epochs=2)]
    for left, right in zip(a, b):
        np.testing.assert_array_equal(left, right)  # same seed -> same schedule
    epoch1 = np.concatenate(a[:4])
    epoch2 = np.concatenate(a[4:])
    assert sorted(epoch1) == sorted(epoch2) == list(range(64))
    assert not np.array_equal(epoch1, epoch2)  # per-epoch reshuffle
