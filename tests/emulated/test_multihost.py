"""Multi-worker remote execution: N job_runner processes forming ONE jax.distributed
runtime (the local analog of a multi-host TPU slice).

This is the ring the reference covers with a Flyte sandbox cluster
(test_flyte_remote.py): real worker processes, real collectives (Gloo over the CPU
backend), real artifact recovery — no hardware.
"""

import json
import textwrap
from pathlib import Path

import pytest

import jax

pytestmark = pytest.mark.skipif(len(jax.devices()) < 8, reason="needs 8 emulated devices")

APP = textwrap.dedent(
    """
    from typing import List

    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax
    import pandas as pd
    from flax import linen as nn
    from flax.training import train_state

    from unionml_tpu import Dataset, Model, MeshSpec, TrainerConfig, make_train_step

    # multi-host rule: every process must compute identical host data, so all
    # randomness (split shuffle included) needs fixed seeds
    dataset = Dataset(name="mh_dataset", test_size=0.2, shuffle=True, random_state=7, targets=["y"])
    model = Model(name="mh_model", dataset=dataset)
    model.__app_module__ = "mh_app:model"

    class MLP(nn.Module):
        @nn.compact
        def __call__(self, x):
            x = nn.relu(nn.Dense(32)(x.astype(jnp.float32)))
            return nn.Dense(2)(x)

    module = MLP()

    @dataset.reader
    def reader(n: int = 512) -> pd.DataFrame:
        rng = np.random.default_rng(0)
        frame = pd.DataFrame({"x1": rng.normal(size=n), "x2": rng.normal(size=n)})
        frame["y"] = (frame["x1"] - frame["x2"] > 0).astype(int)
        return frame

    @model.init
    def init(hyperparameters: dict) -> train_state.TrainState:
        params = module.init(jax.random.PRNGKey(0), jnp.zeros((1, 2)))["params"]
        return train_state.TrainState.create(
            apply_fn=module.apply, params=params,
            tx=optax.adam(hyperparameters.get("learning_rate", 1e-2)),
        )

    def loss_fn(params, batch):
        X, y = batch
        logits = module.apply({"params": params}, X)
        return optax.softmax_cross_entropy_with_integer_labels(logits, y.reshape(-1)).mean()

    # the global mesh spans every device of every process in the slice
    @model.trainer(config=TrainerConfig(epochs=3, batch_size=128, mesh=MeshSpec(data=-1), {trainer_config_extra}))
    def train_step(state, batch):
        return make_train_step(loss_fn)(state, batch)

    @model.predictor
    def predictor(state: train_state.TrainState, features: pd.DataFrame) -> List[float]:
        logits = module.apply({"params": state.params}, jnp.asarray(features.to_numpy()))
        return [float(i) for i in jnp.argmax(logits, -1)]

    @model.evaluator
    def evaluator(state: train_state.TrainState, features: pd.DataFrame, target: pd.DataFrame) -> float:
        logits = module.apply({"params": state.params}, jnp.asarray(features.to_numpy()))
        return float((jnp.argmax(logits, -1) == jnp.asarray(target.squeeze().to_numpy())).mean())
    """
)


def _run_worker_slice(
    tmp_path,
    monkeypatch,
    trainer_config_extra: str,
    app_version: str,
    *,
    n_workers: int = 2,
    devices_per_worker: int = 4,
    wait_kwargs: "dict | None" = None,
):
    app_dir = tmp_path / "appsrc"
    app_dir.mkdir()
    (app_dir / "mh_app.py").write_text(APP.replace("{trainer_config_extra}", trainer_config_extra))
    monkeypatch.syspath_prepend(str(app_dir))
    monkeypatch.chdir(app_dir)
    # each worker emulates a host with devices_per_worker CPU devices
    monkeypatch.setenv("JAX_PLATFORMS", "cpu")
    monkeypatch.setenv("XLA_FLAGS", f"--xla_force_host_platform_device_count={devices_per_worker}")

    import importlib

    import mh_app

    importlib.reload(mh_app)
    model = mh_app.model
    model.remote(backend_store=str(tmp_path / "store"), n_workers=n_workers)

    model.remote_deploy(app_version=app_version)
    execution = model.remote_train(wait=False, hyperparameters={"learning_rate": 0.05})
    assert len(execution.procs) == n_workers
    model._backend.wait(execution, timeout=600, **(wait_kwargs or {}))
    assert execution.status == "SUCCEEDED", (Path(execution.path) / "logs.txt").read_text()[-2000:]
    return model, execution


def _run_two_worker_slice(tmp_path, monkeypatch, trainer_config_extra: str, app_version: str):
    return _run_worker_slice(tmp_path, monkeypatch, trainer_config_extra, app_version)


def test_two_worker_slice_trains_over_global_mesh(tmp_path, monkeypatch):
    model, execution = _run_two_worker_slice(tmp_path, monkeypatch, "", "mh-v1")

    # the workers really formed one 8-device runtime: the worker logs the global
    # device count it observes after jax.distributed.initialize
    log0 = (Path(execution.path) / "logs.txt").read_text()
    assert "joined jax.distributed runtime: process 0/2, global devices 8 (4 local)" in log0

    model.remote_load(execution)
    assert model.artifact.metrics["train"] > 0.9, model.artifact.metrics

    meta = json.loads((Path(execution.path) / "outputs" / "artifact.json").read_text())
    assert meta["metrics"]["test"] > 0.8


def test_two_worker_device_data_steps_per_call(tmp_path, monkeypatch):
    """device_data over a 2-process global mesh: the dataset is globally sharded
    (each process's HBM holds only its row-shards via place_global_array) and the
    multi-step scan dispatch (steps_per_call>1) runs SPMD across both workers."""
    model, execution = _run_two_worker_slice(
        tmp_path, monkeypatch, "device_data=True, steps_per_call=2", "mh-dd-v1"
    )
    log0 = (Path(execution.path) / "logs.txt").read_text()
    assert "device_data over 2 processes" in log0

    model.remote_load(execution)
    assert model.artifact.metrics["train"] > 0.9, model.artifact.metrics


def test_four_worker_slice_trains_over_global_mesh(tmp_path, monkeypatch):
    """Beyond 2 workers: a 4-process x 2-device slice forms one 8-device runtime."""
    model, execution = _run_worker_slice(
        tmp_path, monkeypatch, "", "mh-4w-v1", n_workers=4, devices_per_worker=2
    )
    model.remote_load(execution)
    assert model.artifact.metrics["train"] > 0.9, model.artifact.metrics


def test_multi_worker_single_host_loss_recovers(tmp_path, monkeypatch):
    """Losing ONE worker of a 2-worker slice mid-run: the watchdog detects the dead
    process, reaps the peer blocked in jax.distributed setup/collectives, and the
    resubmitted attempt (with a fresh coordinator) succeeds."""
    monkeypatch.setenv("UNIONML_TPU_FAULT_INJECT", "1")          # attempt 0 dies...
    monkeypatch.setenv("UNIONML_TPU_FAULT_INJECT_PROCESS", "1")  # ...worker 1 only
    model, execution = _run_worker_slice(
        tmp_path,
        monkeypatch,
        "",
        "mh-fault-v1",
        wait_kwargs={"retries": 1},
    )
    assert execution.attempt == 1  # exactly one recovery
    model.remote_load(execution)
    assert model.artifact.metrics["train"] > 0.9, model.artifact.metrics
