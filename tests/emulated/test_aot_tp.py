"""AOT preload on the emulated 8-device mesh.

Oracle: a tp=2 engine warmed from a populated store must serve tokens
bit-identical to a freshly-compiled tp=2 engine (which itself matches the
single-device sequential ``Generator`` run) with ZERO fresh XLA traces; a
``scale_to`` scale-up landing on a submesh the store has seen joins the fleet
without tracing or compiling anything — the elastic-resize path the ISSUE's
acceptance criterion pins.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from unionml_tpu.models import GenerationConfig, Generator, Llama, LlamaConfig, llama_partition_rules
from unionml_tpu.parallel import MeshSpec
from unionml_tpu.serving import ContinuousBatcher, ReplicaSet

pytestmark = pytest.mark.skipif(len(jax.devices()) < 8, reason="needs 8 emulated devices")

PROMPT = [3, 1, 4, 1, 5, 9, 2]


@pytest.fixture(scope="module")
def tiny():
    config = LlamaConfig.tiny(
        vocab_size=96, dim=64, n_layers=2, n_heads=4, n_kv_heads=2, hidden_dim=128,
        dtype=jnp.float32, param_dtype=jnp.float32,
    )
    module = Llama(config)
    params = module.init(jax.random.PRNGKey(1), jnp.zeros((1, 8), jnp.int32))["params"]
    return module, params


def _cfg():
    return GenerationConfig(max_new_tokens=8, temperature=0.0, prompt_buckets=(16,))


def _drain(stream):
    return [int(t) for chunk in stream for t in np.asarray(chunk).ravel()]


def _tp2_engine(module, params, tmp):
    mesh = MeshSpec(model=2).build(devices=jax.devices()[:2])
    gen = Generator(module, params, _cfg(), mesh=mesh, partition_rules=llama_partition_rules())
    return gen, ContinuousBatcher(gen, slots=2, decode_chunk=4, aot=str(tmp))


def test_tp2_preload_then_serve_token_identical(tmp_path, tiny):
    module, params = tiny
    expected = list(Generator(module, params, _cfg())([PROMPT])[0])

    gen1, b1 = _tp2_engine(module, params, tmp_path)
    try:
        b1.warmup()
        assert _drain(b1.submit(PROMPT)) == expected
        assert b1.stats()["aot"]["programs_compiled"] > 0
    finally:
        b1.close()

    # fresh tp=2 engine over the populated store: loads everything, traces nothing
    gen2, b2 = _tp2_engine(module, params, tmp_path)
    try:
        b2.warmup()
        aot = b2.stats()["aot"]
        assert aot["programs_compiled"] == 0 and aot["programs_loaded"] > 0
        assert (gen2.prefill_traces, gen2.decode_traces) == (0, 0)
        assert _drain(b2.submit(PROMPT)) == expected  # AOT == JIT, sharded too
        assert (gen2.prefill_traces, gen2.decode_traces) == (0, 0)
    finally:
        b2.close()


def test_scale_up_preloads_on_reused_submesh(tmp_path, tiny):
    """dp=2 x tp=2 fleet: scale down returns the tail submesh to the spare
    pool; scaling back up re-places onto it and must warm purely from the
    store — zero new XLA traces on the joining replica."""
    module, params = tiny
    expected = list(Generator(module, params, _cfg())([PROMPT])[0])
    mesh = MeshSpec(data=2, model=2).build(devices=jax.devices()[:4])
    rs = ReplicaSet.build(
        module, params, _cfg(), mesh=mesh, partition_rules=llama_partition_rules(),
        replicas=2, slots=2, decode_chunk=4, aot=str(tmp_path),
    )
    try:
        rs.warmup()  # replica 1 compiles + persists its submesh's programs here
        assert rs.scale_to(1) == 1
        assert rs.scale_to(2) == 2
        joined = rs.batchers[1]
        assert (joined.gen.prefill_traces, joined.gen.decode_traces) == (0, 0)
        aot = joined.stats()["aot"]
        assert aot["programs_compiled"] == 0 and aot["programs_loaded"] > 0
        # the rejoined replica serves bit-identically, still without a trace
        assert _drain(joined.submit(PROMPT)) == expected
        assert (joined.gen.prefill_traces, joined.gen.decode_traces) == (0, 0)
        assert rs.stats()["aot"]["programs_loaded"] > 0
    finally:
        rs.close()
