"""Traffic replay end-to-end over a dp=2×tp=2 replica fleet (emulated mesh).

The acceptance leg for docs/workloads.md: a two-tenant scenario mix replayed
through the REAL HTTP stack (ServingApp dispatch — headers, tenancy, SSE)
against a four-chip fleet must

- compute per-tenant SLO verdicts (each tenant's targets from the scenario),
- show tenant-aware session affinity in the fleet's routing stats (a
  tenant's warm turns land on the replica holding its prior sessions),
- stay **token-identical to direct submission**: the tokens a replayed
  stream carried are exactly what ``engine.submit`` produces for the same
  prompt — the replay harness measures the serving stack, it never perturbs
  its output.
"""

import asyncio
import json
import types

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from unionml_tpu.models import GenerationConfig, Llama, LlamaConfig, llama_partition_rules
from unionml_tpu.parallel import MeshSpec
from unionml_tpu.serving import ReplicaSet, ServingApp, TenantRegistry, TenantSpec
from unionml_tpu.serving.tenancy import set_active_registry
from unionml_tpu.workloads import TraceRequest, replay, tenant_verdicts

pytestmark = pytest.mark.skipif(len(jax.devices()) < 8, reason="needs 8 emulated devices")


@pytest.fixture(scope="module")
def tiny():
    config = LlamaConfig.tiny(
        vocab_size=96, dim=64, n_layers=2, n_heads=4, n_kv_heads=2, hidden_dim=128,
        dtype=jnp.float32, param_dtype=jnp.float32,
    )
    module = Llama(config)
    params = module.init(jax.random.PRNGKey(1), jnp.zeros((1, 8), jnp.int32))["params"]
    return module, params


def test_dp2_tp2_replay_verdicts_affinity_and_token_identity(tiny):
    module, params = tiny
    cfg = GenerationConfig(max_new_tokens=6, temperature=0.0, prompt_buckets=(16, 48))
    mesh = MeshSpec(data=2, model=2).build(devices=jax.devices()[:4])
    registry = TenantRegistry({
        "alpha": TenantSpec(slo_ttft_p95_ms=60000.0, slo_shed_ratio=0.01),
        "beta": TenantSpec(slo_ttft_p95_ms=60000.0, slo_shed_ratio=0.01),
    })
    fleet = ReplicaSet.build(
        module, params, cfg,
        mesh=mesh, partition_rules=llama_partition_rules(),
        slots=2, decode_chunk=2, block_size=16, pool_blocks=48,
        prefix_cache=True, max_waiting=64, tenancy=registry,
    )
    set_active_registry(registry)
    model = types.SimpleNamespace(
        artifact=object(), generation_batcher=fleet, _predictor_config=None,
        _compiled_predictor=None, _stream_predictor=None, name="tiny",
    )
    app = ServingApp(model)
    app.tenancy = registry
    app._started = True
    try:
        fleet.warmup()
        # two tenants, two sessions each, three turns per session — the warm
        # turns are what session affinity + the radix tier exist for
        requests = []
        t = 0.0
        for tenant, base in (("alpha", 3), ("beta", 40)):
            for s in range(2):
                for turn in range(3):
                    requests.append(TraceRequest(
                        t=t, prompt=(base + s, base + 7, base + turn),
                        max_tokens=4, tenant=tenant,
                        session=f"{tenant}-{s}", turn=turn,
                    ))
                    t += 0.01
        targets = {
            "alpha": {"ttft_p95_ms": 60000.0, "shed_ratio": 0.01},
            "beta": {"ttft_p95_ms": 60000.0, "shed_ratio": 0.01},
        }
        report = replay(requests, app=app, targets=targets, grace_s=2.0)
        # every request served; both tenants judged and passing
        assert report["requests"] == 12 and report["ok"] == 12
        assert report["verdict_state"] == "pass"
        assert set(report["verdicts"]) == {"alpha", "beta"}
        for verdict in report["verdicts"].values():
            assert verdict["state"] == "pass"
            assert verdict["objectives"]["ttft_p95_ms"]["samples"] == 6
        # the fleet ALSO judged the tenants live: stats carry the same section
        tenant_slo = fleet.stats()["tenant_slo"]
        assert set(tenant_slo) == {"alpha", "beta"}
        assert all(entry["state"] == "ok" for entry in tenant_slo.values())

        # session affinity observed: warm-turn routing left its marks — the
        # tenant map is populated and warm heads were taken (tenant hits
        # and/or actual radix-probe affinity hits, both warm-turn routing)
        sched = fleet.stats()["scheduler"]
        assert sched["tenant_affinity_entries"] == 2
        assert sched["tenant_affinity_hits"] + sched["affinity_hits"] > 0

        # token identity: replaying turn-0 prompts again DIRECTLY through the
        # fleet yields exactly the tokens the HTTP replay streamed (greedy,
        # radix-cache-hit or cold — the whole stack is token-transparent)
        async def http_tokens(prompt, tenant):
            body = json.dumps({
                "prompt": list(prompt), "max_tokens": 4, "stream": True,
            }).encode()
            status, payload, _, _ = await app.server.dispatch_with_headers(
                "POST", "/v1/completions", body, {"x-tenant-id": tenant}
            )
            assert status == 200
            out = []
            async for chunk in payload:
                if not chunk.startswith(b"data: ") or chunk == b"data: [DONE]\n\n":
                    continue
                event = json.loads(chunk[6:])
                text = event["choices"][0].get("text") or ""
                out.extend(int(tok) for tok in text.split())
            return out

        for tenant, base in (("alpha", 3), ("beta", 40)):
            prompt = (base, base + 7, base)
            via_http = asyncio.run(http_tokens(prompt, tenant))
            direct = [
                int(tok)
                for chunk in fleet.submit(list(prompt), max_new_tokens=4, tenant=tenant)
                for tok in np.asarray(chunk).ravel()
            ]
            assert via_http == direct, tenant
    finally:
        set_active_registry(None)
        fleet.close()
