"""Pipeline parallelism on the emulated 8-device CPU mesh.

Correctness oracle: the SPMD pipeline (ppermute rotation under shard_map) must be
numerically equivalent to sequential stage application, forward and backward.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
import optax
from flax import linen as nn
from flax.training import train_state

from unionml_tpu.parallel import MeshSpec, pipeline_apply, sequential_stage_apply, init_stage_params, shard_pytree
from unionml_tpu.models.vit import PipelinedViT, ViTConfig, pipelined_vit_partition_rules

pytestmark = pytest.mark.skipif(len(jax.devices()) < 8, reason="needs 8 emulated devices")


class ToyStage(nn.Module):
    dim: int = 16

    @nn.compact
    def __call__(self, x):
        h = nn.Dense(self.dim * 2, dtype=jnp.float32)(x)
        return x + nn.Dense(self.dim, dtype=jnp.float32)(nn.tanh(h))


@pytest.mark.parametrize("n_stages,n_microbatches", [(4, 4), (2, 4), (8, 2)])
def test_pipeline_matches_sequential(n_stages, n_microbatches):
    mesh = MeshSpec(data=8 // n_stages, pipe=n_stages).build()
    stage = ToyStage()
    x = jax.random.normal(jax.random.PRNGKey(1), (16, 16))
    params = init_stage_params(stage, jax.random.PRNGKey(0), x[:1], n_stages)
    stage_fn = lambda p, h: stage.apply({"params": p}, h)  # noqa: E731

    ref = sequential_stage_apply(stage_fn, params, x)
    out = jax.jit(
        lambda p, h: pipeline_apply(stage_fn, p, h, mesh, n_microbatches=n_microbatches)
    )(params, x)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


def test_pipeline_gradients_match_sequential():
    n_stages, n_microbatches = 4, 4
    mesh = MeshSpec(data=2, pipe=n_stages).build()
    stage = ToyStage()
    x = jax.random.normal(jax.random.PRNGKey(1), (8, 16))
    params = init_stage_params(stage, jax.random.PRNGKey(0), x[:1], n_stages)
    stage_fn = lambda p, h: stage.apply({"params": p}, h)  # noqa: E731

    def loss_pipe(p):
        return jnp.mean(pipeline_apply(stage_fn, p, x, mesh, n_microbatches=n_microbatches) ** 2)

    def loss_seq(p):
        return jnp.mean(sequential_stage_apply(stage_fn, p, x) ** 2)

    g_pipe = jax.jit(jax.grad(loss_pipe))(params)
    g_seq = jax.jit(jax.grad(loss_seq))(params)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-5), g_pipe, g_seq
    )


def test_pipeline_single_device_falls_back_to_sequential():
    mesh = MeshSpec(data=8).build()  # pipe axis size 1
    stage = ToyStage()
    x = jax.random.normal(jax.random.PRNGKey(1), (8, 16))
    params = init_stage_params(stage, jax.random.PRNGKey(0), x[:1], 2)
    stage_fn = lambda p, h: stage.apply({"params": p}, h)  # noqa: E731
    out = pipeline_apply(stage_fn, params, x, mesh, n_microbatches=2)
    ref = sequential_stage_apply(stage_fn, params, x)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-6)


def test_pipelined_vit_train_step():
    """End-to-end: PipelinedViT trains one step over a data×pipe×model mesh with real
    stacked-stage shardings; loss is finite and matches the unpipelined forward."""
    mesh = MeshSpec(data=2, pipe=2, model=2).build()
    config = ViTConfig.tiny(n_layers=4, dtype=jnp.float32)
    model = PipelinedViT(config, n_stages=2, n_microbatches=2)
    images = jax.random.normal(jax.random.PRNGKey(0), (8, 32, 32, 3))
    labels = jnp.arange(8) % config.num_classes
    params = model.init(jax.random.PRNGKey(1), images)

    rules = pipelined_vit_partition_rules()
    # per-stage TP rules must survive the intervening layer_i scope: stacked attention
    # kernels get pipe on the stage dim AND model/fsdp within the stage
    from jax.sharding import PartitionSpec as P

    assert rules.spec_for("stages/layer_0/attn/q_proj/kernel") == P("pipe", "fsdp", "model")
    shardings = rules.shardings(params, mesh)
    params = shard_pytree(params, shardings)
    stage_leaf = jax.tree_util.tree_leaves(params["stages"])[0]
    assert "pipe" in stage_leaf.sharding.spec

    state = train_state.TrainState.create(
        apply_fn=None, params=params, tx=optax.adam(1e-3)
    )

    def loss_fn(p, batch):
        imgs, lbls = batch
        # pass rules: stage params stay sharded at rest over fsdp/model inside the pipeline
        logits = model.apply(p, imgs, mesh, rules)
        return optax.softmax_cross_entropy_with_integer_labels(logits.astype(jnp.float32), lbls).mean()

    @jax.jit
    def step(state, batch):
        loss, grads = jax.value_and_grad(loss_fn)(state.params, batch)
        return state.apply_gradients(grads=grads), loss

    with mesh:
        state2, loss = step(state, (images, labels))
    assert np.isfinite(float(loss))
    # params actually changed
    diff = jax.tree_util.tree_map(lambda a, b: float(jnp.abs(a - b).max()), state.params, state2.params)
    assert max(jax.tree_util.tree_leaves(diff)) > 0


def test_pipeline_param_specs_matches_sequential():
    """Sharded-at-rest stage params (param_specs path: per-stage all-gather inside the
    body) must be numerically identical to the replicated path and the sequential
    oracle, forward and backward."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    n_stages, n_microbatches = 2, 2
    mesh = MeshSpec(data=2, pipe=n_stages, model=2).build()
    stage = ToyStage(dim=16)
    x = jax.random.normal(jax.random.PRNGKey(1), (8, 16))
    params = init_stage_params(stage, jax.random.PRNGKey(0), x[:1], n_stages)
    stage_fn = lambda p, h: stage.apply({"params": p}, h)  # noqa: E731

    # shard kernels over model within each stage; biases carry only the stage dim
    def spec_of(leaf):
        return P("pipe", None, "model") if leaf.ndim == 3 else P("pipe")

    specs = jax.tree_util.tree_map(spec_of, params)
    params = jax.tree_util.tree_map(
        lambda leaf, s: jax.device_put(leaf, NamedSharding(mesh, s)), params, specs
    )

    def loss_pipe(p):
        out = pipeline_apply(
            stage_fn, p, x, mesh, n_microbatches=n_microbatches, param_specs=specs
        )
        return jnp.mean(out**2), out

    def loss_seq(p):
        out = sequential_stage_apply(stage_fn, p, x)
        return jnp.mean(out**2), out

    (_, out), g_pipe = jax.jit(jax.value_and_grad(loss_pipe, has_aux=True))(params)
    (_, ref), g_seq = jax.jit(jax.value_and_grad(loss_seq, has_aux=True))(params)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-5), g_pipe, g_seq
    )


def test_pipeline_param_specs_two_axis_dim_matches_sequential():
    """A dim sharded over a TUPLE of axes (P('pipe', None, ('fsdp','model'))) must
    reconstruct with the PartitionSpec's major-axis-first interleave: the body
    all-gathers minor axis first. Oracle: sequential apply on replicated params."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    n_stages, n_microbatches = 2, 2
    mesh = MeshSpec(data=1, fsdp=2, pipe=n_stages, model=2).build()
    stage = ToyStage(dim=16)
    x = jax.random.normal(jax.random.PRNGKey(1), (8, 16))
    params = init_stage_params(stage, jax.random.PRNGKey(0), x[:1], n_stages)
    stage_fn = lambda p, h: stage.apply({"params": p}, h)  # noqa: E731

    # kernels: output dim sharded over BOTH fsdp and model (4-way on a 16/32-wide dim)
    def spec_of(leaf):
        return P("pipe", None, ("fsdp", "model")) if leaf.ndim == 3 else P("pipe")

    specs = jax.tree_util.tree_map(spec_of, params)
    sharded = jax.tree_util.tree_map(
        lambda leaf, s: jax.device_put(leaf, NamedSharding(mesh, s)), params, specs
    )

    def loss_pipe(p):
        out = pipeline_apply(
            stage_fn, p, x, mesh, n_microbatches=n_microbatches, param_specs=specs
        )
        return jnp.mean(out**2), out

    def loss_seq(p):
        out = sequential_stage_apply(stage_fn, p, x)
        return jnp.mean(out**2), out

    (_, out), g_pipe = jax.jit(jax.value_and_grad(loss_pipe, has_aux=True))(sharded)
    (_, ref), g_seq = jax.jit(jax.value_and_grad(loss_seq, has_aux=True))(params)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-5), g_pipe, g_seq
    )


def test_pipeline_param_specs_rejects_unsharded_stage_dim():
    from jax.sharding import PartitionSpec as P

    mesh = MeshSpec(data=4, pipe=2).build()
    stage = ToyStage(dim=16)
    x = jax.random.normal(jax.random.PRNGKey(1), (8, 16))
    params = init_stage_params(stage, jax.random.PRNGKey(0), x[:1], 2)
    specs = jax.tree_util.tree_map(lambda leaf: P(None, "data"), params)
    with pytest.raises(ValueError, match="stage"):
        pipeline_apply(
            lambda p, h: stage.apply({"params": p}, h), params, x, mesh,
            n_microbatches=2, param_specs=specs,
        )
