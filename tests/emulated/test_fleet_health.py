"""Fleet health on the emulated dp=2 x tp=2 mesh (the ISSUE-8 acceptance
shape): driving synthetic load past a configured TTFT target on one replica
flips its SLO state ok -> breach, pins the offending request's timeline as an
exemplar (/debug/requests?slo=breach), and the replica scheduler measurably
shifts subsequent traffic to the healthy replica — while /debug/fleet and the
Prometheus exposition stay None-free."""

import re

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from unionml_tpu.models import GenerationConfig, Llama, LlamaConfig, llama_partition_rules
from unionml_tpu.observability import FlightRecorder, render_prometheus
from unionml_tpu.observability.health import fleet_debug, fleet_health
from unionml_tpu.observability.slo import SLOConfig
from unionml_tpu.observability.trace import RequestTrace, bind, unbind
from unionml_tpu.parallel import MeshSpec
from unionml_tpu.serving import ReplicaSet

pytestmark = pytest.mark.skipif(len(jax.devices()) < 8, reason="needs 8 emulated devices")

PROMPT_LEN = 12
VOCAB = 96

_PROM_LINE = re.compile(
    r"^(# TYPE [a-zA-Z_:][a-zA-Z0-9_:]* (counter|gauge|summary)|"
    r"[a-zA-Z_:][a-zA-Z0-9_:]*(\{[^}]*\})? -?[0-9.e+-]+(\n)?)$"
)


@pytest.fixture(scope="module")
def replica_set():
    config = LlamaConfig.tiny(
        vocab_size=VOCAB, dim=64, n_layers=2, n_heads=4, n_kv_heads=2, hidden_dim=128,
        dtype=jnp.float32, param_dtype=jnp.float32,
    )
    module = Llama(config)
    params = module.init(jax.random.PRNGKey(1), jnp.zeros((1, 8), jnp.int32))["params"]
    mesh = MeshSpec(data=2, model=2).build(devices=jax.devices()[:4])
    cfg = GenerationConfig(max_new_tokens=8, temperature=0.0, prompt_buckets=(16,))
    rs = ReplicaSet.build(
        module, params, cfg, mesh=mesh, partition_rules=llama_partition_rules(),
        slots=2, decode_chunk=4,
    )
    yield rs
    rs.close()


def _drain(stream) -> int:
    return sum(int(np.asarray(chunk).size) for chunk in stream)


def _no_none(node) -> bool:
    if isinstance(node, dict):
        return all(_no_none(value) for value in node.values())
    if isinstance(node, (list, tuple)):
        return all(_no_none(v) for v in node)
    return node is not None


def test_breach_flips_state_pins_exemplar_and_shifts_routing(replica_set):
    rs = replica_set
    rng = np.random.default_rng(11)
    prompts = [
        [int(t) for t in rng.integers(1, VOCAB, size=PROMPT_LEN)] for _ in range(8)
    ]
    # arm an absurd TTFT target on replica 0 ONLY: any real request breaches it
    # (replica 1 keeps the default unarmed config — a heterogeneous fleet)
    rs.configure_slo(SLOConfig(ttft_p95_ms=1e-4, min_samples=1), replica=0)
    assert rs.batchers[0].health(max_age_s=0)["state"] == "ok"  # armed but idle

    # --- the offending request: traced, routed to replica 0 (idle fleet fills
    # lowest-index first), its TTFT blows the target
    recorder = FlightRecorder(8)
    trace = RequestTrace("slo-victim", "POST", "/gen")
    recorder.start(trace)
    tokens = bind(trace.request_id, trace)
    try:
        produced = _drain(rs.submit(prompts[0]))
    finally:
        unbind(tokens)
    trace.finish(200)
    recorder.complete(trace)
    assert produced > 0
    assert rs._scheduler.submitted[0] == 1  # it DID land on replica 0

    # the timeline self-identified as a breach exemplar, pinned in the ring
    snap = trace.snapshot()
    assert snap["slo_breach"]["objective"] == "ttft_p95_ms"
    assert any(e["event"] == "slo.breach" for e in snap["events"])
    exemplars = recorder.snapshot(slo_breach=True)
    assert [s["request_id"] for s in exemplars["completed"]] == ["slo-victim"]

    # --- replica 0 is now breaching; the fleet view agrees and stays None-free
    assert rs.batchers[0].health(max_age_s=0)["state"] == "breach"
    assert rs.batchers[1].health(max_age_s=0)["state"] == "ok"
    fleet = fleet_health(rs)
    assert fleet["state"] == "breach"
    assert [r["state"] for r in fleet["replicas"]] == ["breach", "ok"]
    assert fleet["worst_score"] < 0.5 <= fleet["replicas"][1]["score"]
    assert _no_none(fleet)
    debug = fleet_debug(rs)
    assert debug["replicas"] == 2 and _no_none(debug)

    # --- the scheduler routes around the breaching replica: every subsequent
    # prompt lands on replica 1 even though replica 0 is equally (un)loaded
    before = list(rs._scheduler.submitted)
    for prompt in prompts[1:]:
        _drain(rs.submit(prompt))
    after = rs._scheduler.submitted
    assert after[0] == before[0], "breaching replica kept receiving traffic"
    assert after[1] == before[1] + len(prompts) - 1
    assert rs.breach_avoided >= len(prompts) - 1

    # --- the merged /metrics view renders as clean Prometheus exposition
    stats = rs.stats()
    assert stats["health"]["state"] == "breach"
    assert stats["breach_avoided"] >= 1
    text = render_prometheus({"requests_total": 0, "errors_total": 0, "generation": stats})
    assert "None" not in text
    for line in text.strip().splitlines():
        assert _PROM_LINE.match(line), f"unparseable exposition line: {line!r}"
    assert "unionml_tpu_generation_health_state_code 2" in text
    assert "unionml_tpu_generation_breach_avoided" in text
